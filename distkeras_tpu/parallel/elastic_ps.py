"""Elastic sharded parameter server — online shard split / merge /
migration with zero training downtime (ISSUE 14 tentpole).

``sharded_ps`` freezes the topology at construction: ``plan_shards``
is a pure function of ``(template, K)``, both endpoints derive it, and
nothing about the partition ever crosses the wire.  This module makes
the partition a first-class, *versioned* object instead:

* a ``ShardMap`` names the current topology — an explicit per-shard
  leaf-index plan (no longer derivable from K), the owning server
  address per shard, a fencing epoch per shard, and a monotonically
  increasing version.  Every client op carries ``(version, shard)``;
  a server that disagrees rejects the op **carrying its own map**, so
  routing repair costs one round trip, not a config push;
* each ``ElasticPSNode`` owns a subset of shards and serves the
  ``"elastic"``-scope wire.  Shard state is the same math as
  ``sharded_ps.commit_shard`` — same clocks, staleness law, telemetry
  and reply caching — but the dedupe cache is **per leaf** (global
  leaf index → ``(seq, reply bytes)``), which is what makes resharding
  exact: a split partitions the cache by leaf, a merge unions it, and
  a retried commit whose ack was lost before a reshard still dedupes
  exactly-once on whatever shard now owns each leaf;
* migration reuses the replicated-PS recipe (``replicated_ps`` /
  ``apply_replicated_shard``): the moving shard's owner keeps serving
  while a ``_Courier`` streams a snapshot plus the tailing commit log
  — entries carry payload bytes, shipped staleness and reply bytes
  verbatim, so the receiver's replay reconstructs center, clocks and
  the dedupe table byte-identically.  At cutover the old owner fences
  the shard with a ``mint_epoch``-minted epoch (stale writers get
  ``PSShardFencedError`` and re-route via the map riding the
  rejection), the residual log drains, and a new map version flips
  ownership.  If the receiver dies mid-move the courier reports dead,
  the old owner un-fences, and training continues — a commit is never
  lost and never applied twice across the move;
* ``ElasticPSGroup`` is the in-process control plane: it owns the
  nodes/servers, builds map versions, and drives ``split`` / ``merge``
  / ``migrate`` / ``add_server`` — the verbs ``telemetry.Autoscaler``
  calls when ``SLOWatchdog`` signals breach.
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

import jax
import numpy as np

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.host_ps import (
    _NO_SEQ,
    _PROBE_WORKER,
    _readonly_view,
    _to_numpy,
    PSShardFencedError,
)
from distkeras_tpu.parallel.replicated_ps import mint_epoch
from distkeras_tpu.parallel.sharded_ps import (
    NEVER_PULLED,
    leaf_nbytes,
    pack_leaves,
    plan_shards,
    unpack_leaves,
)
from distkeras_tpu.parallel.update_rules import PSState, UpdateRule

Pytree = Any


class MigrationAborted(RuntimeError):
    """A shard move could not complete (receiver died / drain timed
    out); the source shard has been un-fenced and keeps serving."""


class ShardMap:
    """One immutable topology version: who owns which leaves, under
    which fencing epoch.  Shard ids are scoped to a version — they are
    renumbered canonically (sorted by first leaf index) every time the
    plan changes, so a ``(version, shard)`` pair is unambiguous."""

    __slots__ = ("version", "plan", "owners", "epochs")

    def __init__(self, version: int, plan: Sequence[Sequence[int]],
                 owners: Sequence[tuple[str, int]],
                 epochs: Sequence[int]):
        if not (len(plan) == len(owners) == len(epochs)):
            raise ValueError(
                f"map arity mismatch: {len(plan)} shards, "
                f"{len(owners)} owners, {len(epochs)} epochs")
        self.version = int(version)
        self.plan = [list(map(int, p)) for p in plan]
        self.owners = [(str(h), int(p)) for h, p in owners]
        self.epochs = [int(e) for e in epochs]

    @property
    def num_shards(self) -> int:
        return len(self.plan)

    def to_obj(self) -> dict:
        return {"version": self.version, "plan": self.plan,
                "owners": [list(o) for o in self.owners],
                "epochs": self.epochs}

    @classmethod
    def from_obj(cls, obj: dict) -> "ShardMap":
        return cls(obj["version"], obj["plan"],
                   [tuple(o) for o in obj["owners"]], obj["epochs"])

    def __repr__(self) -> str:
        return (f"ShardMap(v{self.version}, "
                f"{[len(p) for p in self.plan]} leaves/shard, "
                f"owners={self.owners})")


def _canonical(plan: Sequence[Sequence[int]]) -> list[list[int]]:
    """Shard-id renumbering law: ids sort by first (lowest) leaf index,
    so client and every server agree on shard order within a map
    version without shipping the ordering."""
    return sorted((sorted(int(i) for i in p) for p in plan),
                  key=lambda p: p[0])


class _EShard:
    """One elastic shard: ``sharded_ps._Shard`` plus a per-leaf dedupe
    cache, a per-shard fencing epoch and an optional migration courier.

    ``dedupe[worker][global_leaf_idx] = (seq, reply_bytes)`` — per-leaf
    granularity is the invariant that makes arbitrary resharding
    exactly-once: whatever shard a leaf lands on after any sequence of
    splits/merges/moves, its dedupe entry travels with it."""

    __slots__ = ("idx", "lock", "center", "clock", "pull_clock",
                 "staleness_log", "num_commits", "dedupe",
                 "reply_bytes", "nbytes", "epoch", "fenced", "retired",
                 "courier")

    def __init__(self, idx: Sequence[int], center: list[np.ndarray],
                 epoch: int = 0):
        self.idx = [int(i) for i in idx]
        self.lock = racecheck.lock("elastic_ps.shard")
        self.center = center
        self.clock = 0
        self.pull_clock: dict[int, int] = {}
        self.staleness_log: list[int] = []
        self.num_commits = 0
        self.dedupe: dict[int, dict[int, tuple[int, bytes]]] = {}
        self.reply_bytes = 0
        self.nbytes = leaf_nbytes(center)
        self.epoch = int(epoch)
        self.fenced = False
        self.retired = False
        self.courier: Optional["_Courier"] = None

    def key(self) -> tuple[int, ...]:
        return tuple(self.idx)


STALENESS_LOG_WINDOW = 4096


def _leaf_bytes(x: np.ndarray) -> bytes:
    return np.ascontiguousarray(np.asarray(x)).tobytes()


def _leaf_from_bytes(data: bytes, template: np.ndarray) -> np.ndarray:
    t = np.asarray(template)
    return np.frombuffer(data, dtype=t.dtype).reshape(t.shape)


class _Courier:
    """Migration log shipper: streams one shard's snapshot then tails
    its commit log to the receiving server over the elastic wire —
    the replicated-PS ``_Link`` recipe scoped to one shard move.

    ``append`` is called from inside the shard lock (same law as
    ``ShardedParameterServer.commit_shard``'s replicator ship: the
    log's order matches the shard-lock order, so replay is
    byte-identical); the socket send happens on the courier thread,
    never under the shard lock."""

    #: queue sentinel: pop -> finalize round-trip instead of an append
    _CONFIRM: dict = {"__confirm__": True}

    def __init__(self, addr: tuple[str, int], bootstrap: dict):
        self.addr = (str(addr[0]), int(addr[1]))
        self._bootstrap = bootstrap
        self._cv = racecheck.condition("elastic_ps.courier")
        self._queue: list[dict] = []
        self._inflight = False
        self._bootstrapped = False
        self._confirmed = False
        self._stopping = False
        self.dead = False
        self.error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dkt-shard-courier")

    def start(self) -> "_Courier":
        self._thread.start()
        return self

    def append(self, entry: dict) -> None:
        with self._cv:
            if self.dead or self._stopping:
                return
            self._queue.append(entry)
            self._cv.notify_all()

    def _mark_dead(self, exc: BaseException) -> None:
        with self._cv:
            self.dead = True
            self.error = exc
            self._cv.notify_all()

    def _run(self) -> None:
        try:
            sock = transport.connect(self.addr[0], self.addr[1],
                                     timeout=10.0)
        except Exception as e:
            self._mark_dead(e)
            return
        try:
            transport.send_msg(sock, _PROBE_WORKER.to_bytes(4, "big"))
            transport.send_msg(
                sock, b"B" + transport.pack_obj(self._bootstrap))
            reply = transport.unpack_obj(transport.recv_msg(sock))
            if not reply.get("ok"):
                raise ConnectionError(f"bootstrap refused: {reply!r}")
            with self._cv:
                self._bootstrapped = True
                self._cv.notify_all()
            while True:
                with self._cv:
                    while not self._queue and not self._stopping:
                        self._cv.wait(0.2)
                    if not self._queue and self._stopping:
                        return
                    entry = self._queue.pop(0)
                    self._inflight = True
                try:
                    if entry is self._CONFIRM:
                        transport.send_msg(sock, b"F")
                        reply = transport.unpack_obj(
                            transport.recv_msg(sock))
                        if not reply.get("ok"):
                            raise ConnectionError(
                                f"finalize refused: {reply!r}")
                        with self._cv:
                            self._confirmed = True
                    else:
                        transport.send_msg(
                            sock, b"A" + transport.pack_obj(entry))
                        reply = transport.unpack_obj(
                            transport.recv_msg(sock))
                        if not reply.get("ok"):
                            raise ConnectionError(
                                f"append refused: {reply!r}")
                finally:
                    with self._cv:
                        self._inflight = False
                        self._cv.notify_all()
        except Exception as e:
            self._mark_dead(e)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def drain(self, timeout: float) -> bool:
        """Block until every shipped entry is acked (True) or the
        courier died (False).  Call only after the shard is fenced —
        a fenced shard appends nothing new, so the queue can only
        shrink."""
        deadline = telemetry.now() + float(timeout)
        with self._cv:
            while (not self._bootstrapped or self._queue
                   or self._inflight) and not self.dead:
                left = deadline - telemetry.now()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.2))
            return not self.dead

    def confirm(self, timeout: float) -> bool:
        """Finalize round-trip: prove the receiver is STILL alive and
        answering after the stream went quiet.  Call after ``drain``
        — a quiet courier says nothing about the far end (the receiver
        can die after its last ack), and flipping the map onto a
        corpse strands every client on a dead owner.  Sends ``F`` and
        waits for the ack (True) or death/timeout (False)."""
        with self._cv:
            if self.dead:
                return False
            if not self._confirmed and not any(
                    e is self._CONFIRM for e in self._queue):
                self._queue.append(self._CONFIRM)
                self._cv.notify_all()
        deadline = telemetry.now() + float(timeout)
        with self._cv:
            while not self._confirmed and not self.dead:
                left = deadline - telemetry.now()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.2))
            return not self.dead

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()


class ElasticPSNode:
    """One elastic PS server's state: the shards it owns, the map
    version it believes, and the adopt-side of migration.

    Lock order is ``node lock -> shard lock`` (the node lock guards
    routing — the installed map and the shard tables; each shard's
    data is guarded by its own lock).  The commit path snapshots
    routing under the node lock, releases it, then takes the shard
    lock — so a resharder holding the node lock never deadlocks with
    an in-flight commit, and a commit that loses the race sees the
    shard's ``retired`` flag and re-routes."""

    def __init__(self, rule: UpdateRule, template: Pytree):
        self.rule = rule
        leaves, self._treedef = jax.tree_util.tree_flatten(
            _to_numpy(template))
        self._template_leaves = [np.asarray(x) for x in leaves]
        self._lock = racecheck.lock("elastic_ps.node")
        self.map: Optional[ShardMap] = None
        self.address: Optional[tuple[str, int]] = None
        self._by_leaves: dict[tuple[int, ...], _EShard] = {}
        self._pending: dict[tuple[int, ...], _EShard] = {}
        self._route: dict[int, _EShard] = {}
        self._seen_lock = racecheck.lock("elastic_ps.seen")
        self._last_seen: dict[int, float] = {}

    # -- liveness (mirrors sharded_ps) ---------------------------------

    def _stamp(self, worker_id: int) -> None:
        if worker_id == _PROBE_WORKER:
            return
        with self._seen_lock:
            self._last_seen[worker_id] = telemetry.now()

    def retire(self, worker_id: int) -> None:
        with self._seen_lock:
            self._last_seen.pop(worker_id, None)

    # -- map install / reshard (the control plane face) ----------------

    def _shard_template(self, idx: Sequence[int]) -> list[np.ndarray]:
        return [self._template_leaves[i] for i in idx]

    def bootstrap_owned(self, m: ShardMap) -> None:
        """First install: create fresh shards (template center copies)
        for every shard this node owns in ``m``."""
        with self._lock:
            for sid, idx in enumerate(m.plan):
                if m.owners[sid] != self.address:
                    continue
                key = tuple(idx)
                if key not in self._by_leaves:
                    self._by_leaves[key] = _EShard(
                        idx, [np.array(self._template_leaves[i])
                              for i in idx], epoch=m.epochs[sid])
        self.install_map(m)

    def install_map(self, m: ShardMap) -> None:
        """Adopt a new topology version: owned shards are looked up by
        leaf tuple among live and migration-adopted (pending) shards;
        shards this node no longer owns are retired (a late writer
        holding a stale route gets a stale-map rejection carrying the
        new map, never a lost update)."""
        with self._lock:
            route: dict[int, _EShard] = {}
            for sid, idx in enumerate(m.plan):
                if m.owners[sid] != self.address:
                    continue
                key = tuple(idx)
                shard = self._by_leaves.get(key)
                if shard is None:
                    shard = self._pending.pop(key, None)
                    if shard is None:
                        raise ValueError(
                            f"map v{m.version} says this node owns "
                            f"leaves {key} but no such shard exists "
                            f"(migration bootstrap missing?)")
                    self._by_leaves[key] = shard
                route[sid] = shard
            dropped = [key for key, s in self._by_leaves.items()
                       if s not in route.values()]
            for key in dropped:
                shard = self._by_leaves.pop(key)
                with shard.lock:
                    shard.retired = True
                    if shard.courier is not None:
                        shard.courier.stop()
                        shard.courier = None
            self.map = m
            self._route = route

    def apply_split(self, key: tuple[int, ...], at: int,
                    new_map: ShardMap) -> None:
        """Split the owned shard covering ``key`` at leaf position
        ``at`` and atomically adopt ``new_map``: children inherit the
        parent's clock, pull clocks, staleness window and epoch, and
        the per-leaf dedupe cache partitions between them — under a
        serial (quiescent-boundary) schedule the children behave
        byte-identically to a static run that started at this K."""
        with self._lock:
            parent = self._by_leaves.pop(key)
            parent.lock.acquire()   # waits out any in-flight commit
            try:
                children = []
                for part in (parent.idx[:at], parent.idx[at:]):
                    pos = [parent.idx.index(g) for g in part]
                    child = _EShard(
                        part, [np.array(parent.center[p])
                               for p in pos], epoch=parent.epoch)
                    child.clock = parent.clock
                    child.pull_clock = dict(parent.pull_clock)
                    child.staleness_log = list(parent.staleness_log)
                    child.num_commits = parent.num_commits
                    gset = set(part)
                    for w, entries in parent.dedupe.items():
                        sub = {g: e for g, e in entries.items()
                               if g in gset}
                        if sub:
                            child.dedupe[w] = sub
                            child.reply_bytes += sum(
                                len(b) for _, b in sub.values())
                    children.append(child)
                parent.retired = True
                if parent.courier is not None:
                    parent.courier.stop()
                    parent.courier = None
            finally:
                parent.lock.release()
            for child in children:
                self._by_leaves[child.key()] = child
        self.install_map(new_map)

    def apply_merge(self, key_a: tuple[int, ...],
                    key_b: tuple[int, ...],
                    new_map: ShardMap) -> None:
        """Merge two owned shards and adopt ``new_map``.  The merged
        clock is the max of the parents' and pull clocks take the min
        per worker (staleness stays conservative); at a quiescent
        commit boundary both parents agree on all of these, so the
        merge is exact.  Dedupe caches union per leaf."""
        with self._lock:
            a = self._by_leaves.pop(key_a)
            b = self._by_leaves.pop(key_b)
            a.lock.acquire()
            # lint: allow(lock-order): two instances of the shard lock
            # nest only here, under the node lock, and the data plane
            # holds at most ONE shard lock at a time — no cycle exists
            b.lock.acquire()
            try:
                idx = sorted(a.idx + b.idx)
                by_g = {g: x for g, x in zip(a.idx, a.center)}
                by_g.update({g: x for g, x in zip(b.idx, b.center)})
                merged = _EShard(
                    idx, [np.array(by_g[g]) for g in idx],
                    epoch=max(a.epoch, b.epoch))
                merged.clock = max(a.clock, b.clock)
                for w in set(a.pull_clock) | set(b.pull_clock):
                    merged.pull_clock[w] = min(
                        a.pull_clock.get(w, 0), b.pull_clock.get(w, 0))
                donor = a if len(a.staleness_log) >= \
                    len(b.staleness_log) else b
                merged.staleness_log = list(donor.staleness_log)
                merged.num_commits = max(a.num_commits, b.num_commits)
                for parent in (a, b):
                    for w, entries in parent.dedupe.items():
                        merged.dedupe.setdefault(w, {}).update(entries)
                    parent.retired = True
                    if parent.courier is not None:
                        parent.courier.stop()
                        parent.courier = None
                merged.reply_bytes = sum(
                    len(bts) for entries in merged.dedupe.values()
                    for _, bts in entries.values())
            finally:
                b.lock.release()
                a.lock.release()
            self._by_leaves[merged.key()] = merged
        self.install_map(new_map)

    # -- migration: source side ----------------------------------------

    def start_courier(self, key: tuple[int, ...],
                      dst: tuple[str, int]) -> _Courier:
        with self._lock:
            shard = self._by_leaves[key]
        with shard.lock:
            bootstrap = self._shard_snapshot_locked(shard)
            courier = _Courier(dst, bootstrap).start()
            shard.courier = courier
        return courier

    def _shard_snapshot_locked(self, s: _EShard) -> dict:
        return {
            "idx": list(s.idx),
            "center": pack_leaves(s.center),
            "clock": int(s.clock),
            "pull_clock": {str(w): int(c)
                           for w, c in s.pull_clock.items()},
            "staleness_log": [int(x) for x in s.staleness_log],
            "num_commits": int(s.num_commits),
            "epoch": int(s.epoch),
            "dedupe": {str(w): {str(g): {"seq": int(seq), "reply": b}
                                for g, (seq, b) in entries.items()}
                       for w, entries in s.dedupe.items()},
        }

    def fence_shard(self, key: tuple[int, ...], epoch: int) -> None:
        with self._lock:
            shard = self._by_leaves[key]
        with shard.lock:
            shard.fenced = True
            shard.epoch = max(shard.epoch, int(epoch))
        telemetry.metrics().counter("ps_fenced_total").inc()

    def unfence_shard(self, key: tuple[int, ...]) -> None:
        with self._lock:
            shard = self._by_leaves[key]
        with shard.lock:
            shard.fenced = False
            if shard.courier is not None:
                shard.courier.stop()
                shard.courier = None

    # -- migration: receive side ---------------------------------------

    def adopt_bootstrap(self, obj: dict) -> _EShard:
        idx = [int(i) for i in obj["idx"]]
        shard = _EShard(
            idx, [np.array(x) for x in unpack_leaves(
                self._shard_template(idx), obj["center"])],
            epoch=int(obj["epoch"]))
        shard.clock = int(obj["clock"])
        shard.pull_clock = {int(w): int(c)
                            for w, c in obj["pull_clock"].items()}
        shard.staleness_log = [int(x) for x in obj["staleness_log"]]
        shard.num_commits = int(obj["num_commits"])
        for w, entries in obj["dedupe"].items():
            shard.dedupe[int(w)] = {
                int(g): (int(e["seq"]), bytes(e["reply"]))
                for g, e in entries.items()}
        shard.reply_bytes = sum(
            len(b) for entries in shard.dedupe.values()
            for _, b in entries.values())
        with self._lock:
            self._pending[shard.key()] = shard
        return shard

    def adopt_entry(self, shard: _EShard, entry: dict) -> None:
        """Tail-log replay on the receiving node — the elastic twin of
        ``ShardedParameterServer.apply_replicated_shard``: the shipped
        staleness is applied and the shipped per-leaf reply bytes are
        installed verbatim, so center, clocks and dedupe land
        byte-identical to the source."""
        applied = [int(g) for g in entry["applied"]]
        worker = int(entry["worker"])
        seq = int(entry["seq"])
        staleness = int(entry["staleness"])
        with shard.lock:
            pos = [shard.idx.index(g) for g in applied]
            temps = [shard.center[p] for p in pos]
            leaves = unpack_leaves(temps, entry["payload"])
            state = PSState(
                center=temps, clock=np.int32(shard.clock))
            new_state = self.rule.commit(state, leaves,
                                         np.int32(staleness))
            for p, x in zip(pos, new_state.center):
                shard.center[p] = np.asarray(x)
            shard.clock += 1
            shard.pull_clock[worker] = shard.clock
            shard.staleness_log.append(staleness)
            if len(shard.staleness_log) > \
                    STALENESS_LOG_WINDOW * 5 // 4:
                del shard.staleness_log[:-STALENESS_LOG_WINDOW]
            shard.num_commits += 1
            if seq != _NO_SEQ:
                entries = shard.dedupe.setdefault(worker, {})
                for g, b in entry["dedupe"].items():
                    old = entries.get(int(g))
                    if old is not None:
                        shard.reply_bytes -= len(old[1])
                    entries[int(g)] = (seq, bytes(b))
                    shard.reply_bytes += len(b)

    # -- the data plane -------------------------------------------------

    def _routing(self, map_version: int, sid: int
                 ) -> tuple[Optional[_EShard], ShardMap]:
        with self._lock:
            m = self.map
            if m is None:
                raise ConnectionError("node has no map installed yet")
            if int(map_version) != m.version:
                return None, m
            return self._route.get(int(sid)), m

    def _current_map(self) -> ShardMap:
        with self._lock:
            if self.map is None:
                raise ConnectionError("node has no map installed yet")
            return self.map

    def pull_versioned(self, worker_id: int, map_version: int,
                       since: dict[int, int]) -> dict:
        """Version-delta pull over the shards this node owns: ships
        only shards whose clock advanced past ``since[sid]``
        (``NEVER_PULLED`` forces inclusion); every touched shard
        stamps the worker's pull clock, shipped or skipped."""
        m = self._current_map()
        if int(map_version) != m.version:
            return {"err": "stale", "map": m.to_obj()}
        with self._lock:
            route = dict(self._route)
        tel = telemetry.metrics()
        tel.counter("ps_pulls_total").inc()
        included, skipped, saved = [], 0, 0
        for sid, shard in sorted(route.items()):
            last = int(since.get(sid, NEVER_PULLED))
            with shard.lock:
                if shard.retired:
                    return {"err": "stale",
                            "map": self._current_map().to_obj()}
                shard.pull_clock[worker_id] = shard.clock
                if last != NEVER_PULLED and shard.clock <= last:
                    skipped += 1
                    saved += shard.nbytes
                    continue
                included.append([sid, int(shard.clock),
                                 pack_leaves(shard.center)])
        self._stamp(worker_id)
        if skipped:
            tel.counter("ps_pull_shards_skipped_total").inc(skipped)
            tel.counter("ps_pull_bytes_saved_total").inc(saved)
        return {"ok": True, "inc": included, "skipped": skipped,
                "saved": saved}

    def commit_shard(self, worker_id: int, map_version: int, sid: int,
                     payload: bytes, local: Optional[bytes],
                     seq: Optional[int]) -> dict:
        """One shard's slice of a logical commit — the same math and
        telemetry as ``ShardedParameterServer.commit_shard``, with the
        dedupe check per leaf: leaves whose cached seq already covers
        this commit are served from cache, fresh leaves are applied
        (per-leaf rules make the partial apply exact), and the reply
        is the stitched full-shard pull."""
        shard, m = self._routing(map_version, sid)
        if shard is None:
            return {"err": "stale", "map": m.to_obj()}
        tel = telemetry.metrics()
        wait0 = telemetry.now()
        waiters = tel.gauge("ps_commit_waiters")
        waiters.inc()
        shard.lock.acquire()
        waiters.dec()
        tel.counter("ps_lock_wait_seconds_total").inc(
            telemetry.now() - wait0)
        try:
            with telemetry.span("ps_shard_commit", worker=worker_id,
                                shard=sid):
                if shard.retired:
                    return {"err": "stale",
                            "map": self._current_map().to_obj()}
                if shard.fenced:
                    return {"err": "fenced", "epoch": shard.epoch,
                            "map": m.to_obj()}
                leaves = unpack_leaves(shard.center, payload)
                local_leaves = (None if local is None else
                                unpack_leaves(shard.center, local))
                dmap = shard.dedupe.get(worker_id, {})
                if seq is None:
                    fresh = list(range(len(shard.idx)))
                else:
                    fresh = [p for p, g in enumerate(shard.idx)
                             if g not in dmap or dmap[g][0] < seq]
                if not fresh:
                    self._stamp(worker_id)
                    tel.counter("ps_commit_dedup_total").inc()
                    return {"ok": True, "c": int(shard.clock),
                            "d": b"".join(dmap[g][1]
                                          for g in shard.idx)}
                staleness = shard.clock - shard.pull_clock.get(
                    worker_id, 0)
                sub_center = [shard.center[p] for p in fresh]
                state = PSState(center=sub_center,
                                clock=np.int32(shard.clock))
                new_state = self.rule.commit(
                    state, [leaves[p] for p in fresh],
                    np.int32(staleness))
                pulled = self.rule.worker_pull(
                    None if local_leaves is None
                    else [local_leaves[p] for p in fresh],
                    state.center, new_state.center)
                for p, x in zip(fresh, new_state.center):
                    shard.center[p] = np.asarray(x)
                shard.clock += 1
                shard.pull_clock[worker_id] = shard.clock
                shard.staleness_log.append(int(staleness))
                if len(shard.staleness_log) > \
                        STALENESS_LOG_WINDOW * 5 // 4:
                    del shard.staleness_log[:-STALENESS_LOG_WINDOW]
                shard.num_commits += 1
                tel.counter("ps_shard_commits_total").inc()
                tel.histogram("ps_commit_staleness",
                              buckets=telemetry.STALENESS_BUCKETS
                              ).observe(int(staleness))
                pulled = [np.asarray(x) for x in pulled]
                fresh_bytes = {shard.idx[p]: _leaf_bytes(x)
                               for p, x in zip(fresh, pulled)}
                if seq is not None:
                    entries = shard.dedupe.setdefault(worker_id, {})
                    for g, b in fresh_bytes.items():
                        old = entries.get(g)
                        if old is not None:
                            shard.reply_bytes -= len(old[1])
                        entries[g] = (int(seq), b)
                        shard.reply_bytes += len(b)
                    dmap = entries
                if shard.courier is not None:
                    # under THIS shard's lock, before the reply
                    # escapes: the courier's per-shard log order
                    # matches the lock order, so the receiver's
                    # replay is byte-identical (replicated_ps law)
                    shard.courier.append({
                        "worker": int(worker_id),
                        "seq": _NO_SEQ if seq is None else int(seq),
                        "staleness": int(staleness),
                        "applied": [shard.idx[p] for p in fresh],
                        "payload": pack_leaves(
                            [leaves[p] for p in fresh],
                            [shard.center[p] for p in fresh]),
                        "dedupe": ({} if seq is None else
                                   {str(g): b for g, b
                                    in fresh_bytes.items()}),
                    })
                if sid == m.num_shards - 1:
                    tel.counter("ps_commits_total").inc()
                    # one flight event per LOGICAL commit (its last
                    # shard), mirroring the sharded server
                    # lint: allow(blocking-call-under-lock): acked =>
                    # durable — recorded under the last shard's lock
                    flight_recorder.record(
                        "commit", worker=worker_id, seq=seq,
                        clock=int(shard.clock),
                        shards=m.num_shards,
                        staleness=int(staleness))
                self._stamp(worker_id)
                reply = b"".join(
                    fresh_bytes[g] if g in fresh_bytes
                    else dmap[g][1] for g in shard.idx)
                return {"ok": True, "c": int(shard.clock),
                        "d": reply}
        finally:
            shard.lock.release()

    # -- introspection (control plane / tests) --------------------------

    def owned_leaves(self) -> dict[int, np.ndarray]:
        with self._lock:
            shards = list(self._route.values())
        out: dict[int, np.ndarray] = {}
        for s in shards:
            with s.lock:
                for g, x in zip(s.idx, s.center):
                    out[g] = _readonly_view(x)
        return out

    def shard_stats(self) -> dict[int, dict]:
        with self._lock:
            route = dict(self._route)
        out = {}
        for sid, s in sorted(route.items()):
            with s.lock:
                out[sid] = {"clock": int(s.clock),
                            "num_commits": int(s.num_commits),
                            "nbytes": int(s.nbytes),
                            "fenced": bool(s.fenced),
                            "epoch": int(s.epoch),
                            "leaves": list(s.idx)}
        return out


class ElasticPSServer:
    """TCP front end for one ``ElasticPSNode`` — the ``"elastic"``
    wire scope (handshake: 4-byte worker id, then framed ops).  Body
    encoding is msgpack (``transport.pack_obj``) with parameter
    payloads as raw concatenated leaf bytes inside it, so byte
    identity survives the trip."""

    def __init__(self, node: ElasticPSNode, host: str = "127.0.0.1",
                 port: int = 0):
        import socket as _socket

        self.node = node
        self._sock = _socket.socket()
        self._sock.setsockopt(_socket.SOL_SOCKET,
                              _socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address = self._sock.getsockname()
        node.address = self.address
        self._threads: list[threading.Thread] = []
        self._conns: list = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="dkt-elastic-ps-accept")

    def start(self) -> "ElasticPSServer":
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        import socket as _socket

        try:
            try:
                self._sock.settimeout(0.2)
            except OSError:
                return
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except _socket.timeout:
                    continue
                except OSError:
                    break
                conn.setsockopt(_socket.IPPROTO_TCP,
                                _socket.TCP_NODELAY, 1)
                self._conns.append(conn)
                t = threading.Thread(target=self._serve, args=(conn,),
                                     daemon=True)
                t.start()
                self._threads.append(t)
        finally:
            try:
                self._sock.close()
            except OSError:
                pass

    def _serve(self, conn):
        adopted: Optional[_EShard] = None
        with conn:
            try:
                hello = transport.recv_msg(conn)
                worker_id = int.from_bytes(hello[:4], "big")
                while True:
                    msg = transport.recv_msg(conn)
                    cmd, body = msg[:1], msg[1:]
                    if cmd == b"m":
                        transport.send_msg(conn, transport.pack_obj(
                            self.node._current_map().to_obj()))
                    elif cmd == b"g":
                        req = transport.unpack_obj(body)
                        out = self.node.pull_versioned(
                            worker_id, req["v"],
                            {int(s): int(c) for s, c
                             in req["since"].items()})
                        transport.send_msg(
                            conn, transport.pack_obj(out))
                    elif cmd == b"c":
                        req = transport.unpack_obj(body)
                        seq = int(req["q"])
                        out = self.node.commit_shard(
                            worker_id, req["v"], req["s"], req["d"],
                            req.get("l"),
                            None if seq == _NO_SEQ else seq)
                        transport.send_msg(
                            conn, transport.pack_obj(out))
                    elif cmd == b"B":
                        adopted = self.node.adopt_bootstrap(
                            transport.unpack_obj(body))
                        transport.send_msg(
                            conn, transport.pack_obj({"ok": True}))
                    elif cmd == b"A":
                        if adopted is None:
                            raise ValueError(
                                "migrate_append before bootstrap")
                        self.node.adopt_entry(
                            adopted, transport.unpack_obj(body))
                        transport.send_msg(
                            conn, transport.pack_obj({"ok": True}))
                    elif cmd == b"F":
                        # finalize: the courier proves this end is
                        # still alive before the cutover flips the map
                        transport.send_msg(conn, transport.pack_obj(
                            {"ok": adopted is not None}))
                    elif cmd == b"d":
                        self.node.retire(worker_id)
                    elif cmd == b"s":
                        self._stop.set()
                        return
                    else:
                        raise ValueError(f"unknown command {cmd!r}")
            except (ConnectionError, OSError):
                return
            except Exception as e:
                import sys

                print(f"[distkeras_tpu] elastic PS handler error "
                      f"(connection dropped): {e!r}", file=sys.stderr,
                      flush=True)
                return

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def kill(self):
        """Crash simulation: drop the listener and every live
        connection mid-exchange (the chaos drill kills the RECEIVING
        server of a migration this way — the courier sees a
        ``ConnectionError`` and the move aborts cleanly)."""
        flight_recorder.record(
            "ps_kill", port=self.address[1],
            num_commits=sum(
                s["num_commits"]
                for s in self.node.shard_stats().values()))
        flight_recorder.flush(fsync=True)
        self._stop.set()
        for s in (self._sock, *self._conns):
            try:
                s.close()
            except OSError:
                pass


def fetch_shard_map(host: str, port: int,
                    timeout: float = 10.0) -> ShardMap:
    """One-shot map fetch from any elastic server (the routing-table
    refresh ``ResilientPSClient`` performs on a shard-fence
    rejection)."""
    sock = transport.connect(host, port, timeout=timeout)
    try:
        transport.send_msg(sock, _PROBE_WORKER.to_bytes(4, "big"))
        transport.send_msg(sock, b"m")
        return ShardMap.from_obj(
            transport.unpack_obj(transport.recv_msg(sock)))
    finally:
        sock.close()


class ElasticPSClient:
    """Worker-side connection(s) speaking the elastic wire.

    Same face as ``PSClient``/``ShardedPSClient`` so
    ``ResilientPSClient`` wraps it unchanged, plus the elastic verbs:
    ``refresh_map`` re-pulls the shard map (from current owners first,
    then the seed addresses) and ``apply_shard_map`` installs a map
    that rode a fence rejection.  Commits walk the map's shards in id
    order with ONE logical seq, grouped per owner connection; a
    ``fenced``/``stale`` reply raises ``PSShardFencedError`` carrying
    the server's map, which ``ResilientPSClient`` turns into a
    refresh-and-retry instead of a burned retry attempt."""

    def __init__(self, seeds: Sequence[tuple[str, int]],
                 worker_id: int, template: Pytree,
                 stats: Optional[dict] = None):
        self.worker_id = int(worker_id)
        leaves, self._treedef = jax.tree_util.tree_flatten(
            _to_numpy(template))
        self._template_leaves = [np.asarray(x) for x in leaves]
        self._seeds = [(str(h), int(p)) for h, p in seeds]
        self._conns: dict[tuple[str, int], Any] = {}
        self._stats = stats if stats is not None else {}
        self._stats.setdefault("pull_shards_skipped", 0)
        self._stats.setdefault("pull_bytes_saved", 0)
        self.map: Optional[ShardMap] = None
        # leaf tuple -> (clock, leaves): survives map changes, so a
        # reshard only re-pulls shards whose leaf grouping changed
        self._cache: dict[tuple[int, ...],
                          tuple[int, list[np.ndarray]]] = {}
        self.refresh_map()

    # -- connections ----------------------------------------------------

    def _conn(self, addr: tuple[str, int]):
        sock = self._conns.get(addr)
        if sock is None:
            sock = transport.connect(addr[0], addr[1], timeout=30.0)
            transport.send_msg(
                sock, int(self.worker_id).to_bytes(4, "big"))
            self._conns[addr] = sock
        return sock

    def _drop_conn(self, addr: tuple[str, int]) -> None:
        sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- the routing table ----------------------------------------------

    def refresh_map(self) -> ShardMap:
        candidates: list[tuple[str, int]] = []
        if self.map is not None:
            candidates.extend(dict.fromkeys(self.map.owners))
        candidates.extend(a for a in self._seeds
                          if a not in candidates)
        last: Optional[Exception] = None
        for addr in candidates:
            try:
                sock = self._conn(addr)
                transport.send_msg(sock, b"m")
                obj = transport.unpack_obj(transport.recv_msg(sock))
            except Exception as e:
                last = e
                self._drop_conn(addr)
                continue
            self.apply_shard_map(obj)
            return self.map
        raise ConnectionError(
            f"no elastic PS address answered a map fetch "
            f"(tried {candidates}): {last!r}")

    def apply_shard_map(self, obj: dict | ShardMap) -> None:
        m = obj if isinstance(obj, ShardMap) else \
            ShardMap.from_obj(obj)
        if self.map is not None and m.version < self.map.version:
            return  # never step routing backwards
        self.map = m
        telemetry.metrics().counter("ps_map_refresh_total").inc()

    def _shard_template(self, idx: Sequence[int]) -> list[np.ndarray]:
        return [self._template_leaves[i] for i in idx]

    def _raise_rejection(self, out: dict, sid: int) -> None:
        err = out.get("err", "fenced")
        raise PSShardFencedError(
            f"shard {sid} rejected the op ({err}): the routing "
            f"table moved under this client",
            shard=sid, map_obj=out.get("map"))

    # -- the client face -------------------------------------------------

    def pull(self) -> Pytree:
        m = self.map
        by_owner: dict[tuple[str, int], dict[str, int]] = {}
        for sid, idx in enumerate(m.plan):
            cached = self._cache.get(tuple(idx))
            by_owner.setdefault(m.owners[sid], {})[str(sid)] = (
                NEVER_PULLED if cached is None else cached[0])
        with telemetry.span("ps_client_pull", worker=self.worker_id):
            for addr, since in by_owner.items():
                sock = self._conn(addr)
                try:
                    transport.send_msg(
                        sock, b"g" + transport.pack_obj(
                            {"v": m.version, "since": since}))
                    out = transport.unpack_obj(
                        transport.recv_msg(sock))
                except Exception:
                    self._drop_conn(addr)
                    raise
                if not out.get("ok"):
                    self._raise_rejection(out, -1)
                for sid, clock, data in out["inc"]:
                    idx = m.plan[int(sid)]
                    self._cache[tuple(idx)] = (
                        int(clock),
                        unpack_leaves(self._shard_template(idx),
                                      data))
                self._stats["pull_shards_skipped"] += int(
                    out.get("skipped", 0))
                self._stats["pull_bytes_saved"] += int(
                    out.get("saved", 0))
        return self._assemble(m)

    def _assemble(self, m: ShardMap) -> Pytree:
        out: list = [None] * len(self._template_leaves)
        for idx in m.plan:
            got = self._cache.get(tuple(idx))
            if got is None:
                raise ConnectionError(
                    f"no cached copy of shard leaves {idx} "
                    f"(pull before assemble)")
            for g, x in zip(idx, got[1]):
                out[g] = x
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def commit(self, payload, local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        m = self.map
        wire_seq = _NO_SEQ if seq is None else int(seq)
        leaves = jax.tree_util.tree_leaves(_to_numpy(payload))
        local_leaves = (None if local is None else
                        jax.tree_util.tree_leaves(_to_numpy(local)))
        with telemetry.span("ps_client_commit",
                            worker=self.worker_id, seq=seq):
            for sid, idx in enumerate(m.plan):
                temps = self._shard_template(idx)
                body = {
                    "v": m.version, "s": sid, "q": wire_seq,
                    "d": pack_leaves([leaves[g] for g in idx],
                                     temps),
                }
                if local_leaves is not None:
                    body["l"] = pack_leaves(
                        [local_leaves[g] for g in idx], temps)
                addr = m.owners[sid]
                sock = self._conn(addr)
                try:
                    transport.send_msg(
                        sock, b"c" + transport.pack_obj(body))
                    out = transport.unpack_obj(
                        transport.recv_msg(sock))
                except Exception:
                    self._drop_conn(addr)
                    raise
                if not out.get("ok"):
                    self._raise_rejection(out, sid)
                self._cache[tuple(idx)] = (
                    int(out["c"]), unpack_leaves(temps, out["d"]))
        return self._assemble(m)

    def done(self) -> None:
        for addr in list(self._conns):
            try:
                transport.send_msg(self._conns[addr], b"d")
            except Exception:
                pass

    def close(self) -> None:
        for addr in list(self._conns):
            self._drop_conn(addr)


class ElasticPSGroup:
    """In-process control plane for a fleet of elastic PS servers:
    owns the nodes, mints map versions, and drives the reshard verbs.
    The data plane stays on real sockets (workers connect to the
    member servers), so chaos can kill a member mid-move.

    ``split``/``merge`` re-partition in place on the owning node;
    ``migrate`` streams a shard to another member with zero downtime
    (``start_migration`` + ``cutover`` are exposed separately so the
    chaos drill can kill the receiver in between)."""

    def __init__(self, rule: UpdateRule, center: Pytree,
                 num_shards: int = 1, num_servers: int = 1, *,
                 host: str = "127.0.0.1", placement: str = "first",
                 epoch_group: int = 16):
        if num_servers < 1:
            raise ValueError("num_servers must be >= 1")
        self.rule = rule
        self._center_template = _to_numpy(center)
        leaves = jax.tree_util.tree_leaves(self._center_template)
        self._treedef = jax.tree_util.tree_structure(
            self._center_template)
        self._n_leaves = len(leaves)
        self._epoch_group = int(epoch_group)
        self._lock = racecheck.lock("elastic_ps.group")
        self.nodes: list[ElasticPSNode] = []
        self.servers: list[ElasticPSServer] = []
        for _ in range(num_servers):
            node = ElasticPSNode(rule, self._center_template)
            self.nodes.append(node)
            self.servers.append(
                ElasticPSServer(node, host=host).start())
        plan = _canonical(plan_shards(leaves, num_shards))
        if placement == "first":
            owners = [self.servers[0].address] * len(plan)
        elif placement == "spread":
            owners = [self.servers[i % num_servers].address
                      for i in range(len(plan))]
        else:
            raise ValueError(f"unknown placement {placement!r}")
        m = ShardMap(1, plan, owners, [0] * len(plan))
        for node in self.nodes:
            node.bootstrap_owned(m)
        self.map = m
        self._migrations: dict[int, dict] = {}

    # -- addressing ------------------------------------------------------

    @property
    def addresses(self) -> list[tuple[str, int]]:
        return [s.address for s in self.servers]

    @property
    def num_shards(self) -> int:
        return self.map.num_shards

    def _node_for(self, addr: tuple[str, int]) -> ElasticPSNode:
        for node in self.nodes:
            if node.address == tuple(addr):
                return node
        raise KeyError(f"no group member at {addr}")

    def _install_everywhere(self, m: ShardMap,
                            skip: Sequence[ElasticPSNode] = ()
                            ) -> None:
        for node in self.nodes:
            if node not in skip:
                node.install_map(m)
        self.map = m

    def _renumber(self, plan: list[list[int]],
                  owners: dict[tuple[int, ...], tuple[str, int]],
                  epochs: dict[tuple[int, ...], int],
                  version: int) -> ShardMap:
        new_plan = _canonical(plan)
        return ShardMap(
            version, new_plan,
            [owners[tuple(p)] for p in new_plan],
            [epochs[tuple(p)] for p in new_plan])

    def _map_pieces(self):
        m = self.map
        owners = {tuple(p): m.owners[i] for i, p in enumerate(m.plan)}
        epochs = {tuple(p): m.epochs[i] for i, p in enumerate(m.plan)}
        return [list(p) for p in m.plan], owners, epochs

    # -- reshard verbs ---------------------------------------------------

    def split(self, sid: int, at: Optional[int] = None) -> ShardMap:
        """Split shard ``sid`` at leaf position ``at`` (default: half
        by leaf count) into two shards on the same owner."""
        with self._lock:
            m = self.map
            idx = m.plan[sid]
            if len(idx) < 2:
                raise ValueError(
                    f"shard {sid} has {len(idx)} leaf; cannot split")
            at = len(idx) // 2 if at is None else int(at)
            if not 0 < at < len(idx):
                raise ValueError(
                    f"split point {at} outside (0, {len(idx)})")
            plan, owners, epochs = self._map_pieces()
            key = tuple(plan.pop(sid))
            left, right = list(key[:at]), list(key[at:])
            plan.extend([left, right])
            owner = owners.pop(key)
            epoch = epochs.pop(key)
            for part in (left, right):
                owners[tuple(part)] = owner
                epochs[tuple(part)] = epoch
            new_map = self._renumber(plan, owners, epochs,
                                     m.version + 1)
            node = self._node_for(owner)
            node.apply_split(key, at, new_map)
            self._install_everywhere(new_map, skip=(node,))
        telemetry.metrics().counter("elastic_reshards_total",
                                    kind="split").inc()
        flight_recorder.record(
            "shard_split", shard=int(sid), at=int(at),
            version=new_map.version,
            sizes=[len(left), len(right)])
        return new_map

    def merge(self, sid_a: int, sid_b: int) -> ShardMap:
        """Merge two shards owned by the same server into one."""
        with self._lock:
            m = self.map
            if sid_a == sid_b:
                raise ValueError("cannot merge a shard with itself")
            if m.owners[sid_a] != m.owners[sid_b]:
                raise ValueError(
                    f"shards {sid_a} and {sid_b} live on different "
                    f"servers ({m.owners[sid_a]} vs {m.owners[sid_b]}"
                    f"); migrate one first")
            plan, owners, epochs = self._map_pieces()
            key_a, key_b = tuple(m.plan[sid_a]), tuple(m.plan[sid_b])
            plan = [p for i, p in enumerate(plan)
                    if i not in (sid_a, sid_b)]
            merged = sorted(key_a + key_b)
            plan.append(merged)
            owner = owners.pop(key_a)
            owners.pop(key_b)
            epoch = max(epochs.pop(key_a), epochs.pop(key_b))
            owners[tuple(merged)] = owner
            epochs[tuple(merged)] = epoch
            new_map = self._renumber(plan, owners, epochs,
                                     m.version + 1)
            node = self._node_for(owner)
            node.apply_merge(key_a, key_b, new_map)
            self._install_everywhere(new_map, skip=(node,))
        telemetry.metrics().counter("elastic_reshards_total",
                                    kind="merge").inc()
        flight_recorder.record(
            "shard_merge", shards=[int(sid_a), int(sid_b)],
            version=new_map.version, leaves=len(merged))
        return new_map

    def add_server(self, host: str = "127.0.0.1") -> int:
        """Grow the fleet by one (empty) member; returns its index.
        The new node adopts the current map (owning nothing) so it can
        serve map fetches and receive migrations immediately."""
        with self._lock:
            node = ElasticPSNode(self.rule, self._center_template)
            server = ElasticPSServer(node, host=host).start()
            node.install_map(self.map)
            self.nodes.append(node)
            self.servers.append(server)
            return len(self.servers) - 1

    # -- migration -------------------------------------------------------

    def start_migration(self, sid: int, dst: int) -> None:
        """Begin streaming shard ``sid`` to member ``dst``: snapshot +
        tail log, while the source keeps serving (zero downtime)."""
        with self._lock:
            m = self.map
            src_addr = m.owners[sid]
            dst_addr = self.servers[dst].address
            if src_addr == dst_addr:
                raise ValueError(
                    f"shard {sid} already lives on member {dst}")
            if sid in self._migrations:
                raise ValueError(f"shard {sid} is already migrating")
            key = tuple(m.plan[sid])
            src = self._node_for(src_addr)
            courier = src.start_courier(key, dst_addr)
            self._migrations[sid] = {
                "key": key, "src": src, "dst": dst,
                "dst_addr": dst_addr, "courier": courier,
                "t0": telemetry.now(), "version": m.version}
        flight_recorder.record(
            "shard_migrate_begin", shard=int(sid),
            src=list(src_addr), dst=list(dst_addr),
            version=m.version)

    def cutover(self, sid: int, timeout: float = 30.0) -> ShardMap:
        """Fence the moving shard, drain the residual log, flip the
        map.  If the receiver died (or the drain timed out) the source
        un-fences and keeps the shard — raises ``MigrationAborted``
        and training continues against the old topology."""
        with self._lock:
            mig = self._migrations.pop(sid, None)
            if mig is None:
                raise ValueError(f"no migration in flight for shard "
                                 f"{sid}")
            m = self.map
            key, src, courier = mig["key"], mig["src"], mig["courier"]
            src_idx = self.nodes.index(src)
            minted = mint_epoch(
                m.epochs[sid], max(m.epochs), src_idx,
                max(self._epoch_group, len(self.nodes)))
            src.fence_shard(key, minted)
            # drain proves every entry was acked; confirm proves the
            # receiver is STILL answering — without it a receiver that
            # dies after its last ack gets the map flipped onto it
            aborted = not (courier.drain(timeout)
                           and courier.confirm(timeout))
            if aborted:
                src.unfence_shard(key)
                telemetry.metrics().counter(
                    "elastic_migrations_aborted_total").inc()
            else:
                new_map = self._cutover_locked(mig, key, minted, m)
                latency = telemetry.now() - mig["t0"]
        if aborted:
            flight_recorder.record(
                "shard_migrate_abort", shard=int(sid),
                dst=list(mig["dst_addr"]),
                error=repr(courier.error))
            raise MigrationAborted(
                f"receiver {mig['dst_addr']} did not take shard "
                f"{sid}: {courier.error!r}; source un-fenced, "
                f"old topology still serving")
        telemetry.metrics().counter("elastic_reshards_total",
                                    kind="migrate").inc()
        telemetry.metrics().histogram(
            "elastic_migration_seconds").observe(latency)
        flight_recorder.record(
            "shard_migrate_cutover", shard=int(sid),
            dst=list(mig["dst_addr"]), epoch=int(minted),
            version=new_map.version, latency_s=float(latency))
        return new_map

    def _cutover_locked(self, mig: dict, key: tuple[int, ...],
                        minted: int, m: ShardMap) -> ShardMap:
        mig["courier"].stop()
        plan, owners, epochs = self._map_pieces()
        owners[key] = mig["dst_addr"]
        epochs[key] = minted
        new_map = self._renumber(plan, owners, epochs,
                                 m.version + 1)
        # receiver first (activates its pending shard), source
        # last (retires its copy only after the new owner routes)
        self.nodes[mig["dst"]].install_map(new_map)
        self._install_everywhere(
            new_map, skip=(self.nodes[mig["dst"]],))
        return new_map

    def migrate(self, sid: int, dst: int,
                timeout: float = 30.0) -> ShardMap:
        self.start_migration(sid, dst)
        return self.cutover(sid, timeout)

    # -- introspection ---------------------------------------------------

    @property
    def center(self) -> Pytree:
        out: list = [None] * self._n_leaves
        for node in self.nodes:
            for g, x in node.owned_leaves().items():
                out[g] = x
        missing = [g for g, x in enumerate(out) if x is None]
        if missing:
            raise RuntimeError(f"leaves {missing} have no live owner")
        return jax.tree_util.tree_unflatten(self._treedef, out)

    @property
    def num_commits(self) -> int:
        """Logical commits: shard 0 of the current map (every logical
        commit touches every shard, so any one shard counts them)."""
        owner = self._node_for(self.map.owners[0])
        return owner.shard_stats()[0]["num_commits"]

    def shard_stats(self) -> dict:
        out = {}
        for node in self.nodes:
            out.update(node.shard_stats())
        return out

    def stop(self) -> None:
        for server in self.servers:
            server.stop()

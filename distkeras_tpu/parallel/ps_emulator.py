"""On-mesh emulation of the asynchronous parameter server.

The reference's PS is a threaded TCP server on the Spark driver: workers
race to commit deltas, and staleness is whatever the race produced
(SURVEY.md §3.2).  An XLA program is synchronous, so the rebuild makes the
race *explicit*: each emulated round, every worker runs a communication
window of local steps on its mesh slice, and the server applies the
resulting commits in a per-round permuted order.  The i-th commit in that
order has staleness i — the same quantity the reference's DynSGD server
reads off its global update counter, but deterministic and replayable
(SURVEY.md §7, design 5b).

Two fidelities:

* ``faithful`` — commits applied sequentially via ``lax.scan``
  (``update_rules.apply_commit_round_pulls``); each worker's pull sees
  exactly the center its commit position implies.  Bit-for-bit the
  reference's handler-thread serialization, minus nondeterminism.  The
  pulls are computed inside the scan, so memory is O(params) carry plus
  the worker-parameter output the round produces anyway — the flagship
  model fits (VERDICT.md round-1 Weak #3 fixed).
* ``fast`` — closed-form equivalent for the linear rules: the round's
  center update collapses to one weighted sum (a single ``psum``-shaped
  reduction on the mesh), and every worker pulls the round-final center
  (i.e. pulls are deferred to the round barrier; for the elastic family
  the worker-side move uses the round-start center).  The *center*
  trajectory is exact for DOWNPOUR/ADAG/DynSGD and exact-in-expectation
  for the elastic family; only pull timing differs.  O(params) memory.

Sharding: callers jit the returned round function with the stacked worker
axis sharded over the mesh's ``workers`` axis (``distkeras_tpu.mesh``).
XLA then lowers the payload reduction to an ICI all-reduce and the
faithful path's gathers to all-gathers — the collective layout recommended
by the scaling-book recipe (mesh + shardings, compiler inserts
collectives).
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp

from distkeras_tpu import telemetry
from distkeras_tpu.parallel.update_rules import (
    DynSGDRule,
    ElasticRule,
    PSState,
    UpdateRule,
    apply_commit_round_pulls,
)
from distkeras_tpu.utils import tree_sub
from distkeras_tpu.workers import TrainState, make_window_runner

Pytree = Any


def _broadcast_like(tree: Pytree, num: int) -> Pytree:
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (num, *x.shape)), tree)


def _take(tree: Pytree, idx) -> Pytree:
    return jax.tree_util.tree_map(lambda x: x[idx], tree)


def make_round_fn(rule: UpdateRule, step_fn: Callable,
                  fidelity: str = "faithful") -> Callable:
    """Build the emulated-round function.

    ``round_fn(ps_state, worker_states, batches, perm)`` where

    * ``ps_state`` — ``PSState`` (center params + commit clock),
    * ``worker_states`` — ``TrainState`` stacked ``[W, ...]``,
    * ``batches`` — column dict, leaves ``[W, window, B, ...]``,
    * ``perm`` — ``[W]`` int32, this round's commit order
      (``perm[i]`` = worker committing i-th).

    Returns ``(ps_state, worker_states, metrics)``; ``metrics`` includes
    per-worker mean loss and the per-worker staleness this round.
    """
    if fidelity not in ("faithful", "fast"):
        raise ValueError(f"unknown fidelity {fidelity!r}")
    window_run = make_window_runner(step_fn)

    def round_fn(ps_state: PSState, worker_states: TrainState,
                 batches: Mapping[str, jnp.ndarray], perm: jnp.ndarray):
        # Python side effect at TRACE time only: the emulated arms run
        # whole rounds as one XLA program, so "compiles per fidelity"
        # is the honest host-visible counter (per-round spans live in
        # the trainer loop, which drives this program from the host).
        telemetry.metrics().counter("ps_round_compiles_total",
                                    fidelity=fidelity).inc()
        num_workers = perm.shape[0]
        window = jax.tree_util.tree_leaves(batches)[0].shape[1]
        center = ps_state.center

        if rule.payload_kind == "delta":
            # Round-start pull: every worker adopts the current center.
            pulled = _broadcast_like(center, num_workers)
            worker_states = worker_states.replace(params=pulled)

        new_states, step_metrics = jax.vmap(window_run)(
            worker_states, batches)

        if rule.payload_kind == "delta":
            payloads = rule.normalize_delta(
                tree_sub(new_states.params, pulled), window)
        else:
            payloads = new_states.params

        inv = jnp.argsort(perm)  # inv[w] = commit position of worker w

        if fidelity == "faithful":
            ordered = _take(payloads, perm)
            ordered_locals = (_take(new_states.params, perm)
                              if rule.pull_uses_local else None)
            ps_state, ordered_pulled = apply_commit_round_pulls(
                rule, ps_state, ordered, ordered_locals)
            pulled_params = _take(ordered_pulled, inv)
        else:
            ps_state, pulled_params = _fast_round(
                rule, ps_state, payloads, new_states.params, inv,
                num_workers)

        new_states = new_states.replace(params=pulled_params)
        metrics = {
            "loss": step_metrics["loss"].mean(axis=1),        # [W]
            "grad_norm": step_metrics["grad_norm"].mean(axis=1),
            "staleness": inv.astype(jnp.int32),               # [W]
        }
        return ps_state, new_states, metrics

    return round_fn


def make_pipelined_round_fn(rule: UpdateRule,
                            step_fn: Callable) -> Callable:
    """Commit-pipelined emulated round (VERDICT r4 #2: overlap the
    commit round with the next window's compute).

    Round ``k``'s window and round ``k-1``'s commit scan are two
    INDEPENDENT subgraphs of one jitted program: the window consumes
    the pulls of round ``k-2``'s commits (carried in
    ``worker_states``), while the commit scan folds round ``k-1``'s
    payloads into the center.  XLA is free to interleave the commit
    scan's HBM-bound tree updates with the window's MXU-bound convs —
    the on-chip analogue of the reference's worker threads computing
    while the PS thread serviced other commits.

    Semantics: every commit lands exactly one round later than the
    in-order emulator, i.e. uniform +W staleness (W = workers/round),
    which is passed into the rule as ``staleness_offset`` so
    staleness-aware rules (DynSGD) scale by the TRUE commit depth.
    Pulls are round-barrier pulls (every worker adopts the post-round
    center).  Delta-payload rules only: the elastic family's commit
    reads the committing worker's CURRENT local params, which is a
    read-modify-write against the window itself — structurally
    serial, no pipelining exists (measured discussion in PERF.md
    §15 addendum).

    ``round_fn(ps_state, worker_states, batches, perm, pending,
    pending_perm, pending_valid)`` returns ``(ps_state,
    worker_states, metrics, payloads, perm, valid)`` — thread the
    last three back in as the next round's pending commit, and flush
    the final pending with ``flush_pending`` after the last round.
    """
    if rule.payload_kind != "delta":
        raise ValueError(
            "commit pipelining supports the delta-payload family "
            "(DOWNPOUR/ADAG/DynSGD); the elastic family's commits "
            "read the committing worker's current locals — a "
            "read-modify-write against the running window, which "
            "cannot overlap")
    window_run = make_window_runner(step_fn)

    def round_fn(ps_state: PSState, worker_states: TrainState,
                 batches: Mapping[str, jnp.ndarray], perm: jnp.ndarray,
                 pending: Pytree, pending_perm: jnp.ndarray,
                 pending_valid: jnp.ndarray):
        # trace-time compile counter (see make_round_fn)
        telemetry.metrics().counter("ps_round_compiles_total",
                                    fidelity="pipelined").inc()
        num_workers = perm.shape[0]
        window = jax.tree_util.tree_leaves(batches)[0].shape[1]
        start = worker_states.params  # pulls adopted at last round end

        # window k: depends only on worker_states/batches
        new_states, step_metrics = jax.vmap(window_run)(
            worker_states, batches)
        payloads = rule.normalize_delta(
            tree_sub(new_states.params, start), window)

        # commit k-1: depends only on ps_state/pending — independent
        def commit(ps):
            ordered = _take(pending, pending_perm)
            ps2, _ = apply_commit_round_pulls(
                rule, ps, ordered, None,
                staleness_offset=num_workers)
            return ps2

        ps_state = jax.lax.cond(pending_valid, commit, lambda ps: ps,
                                ps_state)
        # round-barrier pull of the post-commit center
        new_states = new_states.replace(
            params=_broadcast_like(ps_state.center, num_workers))
        inv = jnp.argsort(perm)
        metrics = {
            "loss": step_metrics["loss"].mean(axis=1),
            "grad_norm": step_metrics["grad_norm"].mean(axis=1),
            # true commit depth: one full round behind + position
            "staleness": (inv + num_workers).astype(jnp.int32),
        }
        return (ps_state, new_states, metrics, payloads, perm,
                jnp.asarray(True))

    return round_fn


def flush_pending(rule: UpdateRule, ps_state: PSState, pending: Pytree,
                  pending_perm: jnp.ndarray, num_workers: int
                  ) -> PSState:
    """Apply the final round's still-pending commits (the pipelined
    round always runs one commit behind).

    At the drain no younger window intervenes, so the pending commits
    land at their TRUE depth — position in the commit order only,
    ``staleness_offset=0`` — unlike mid-training rounds, whose +W
    offset reflects the window that ran ahead of them (ADVICE.md r5:
    the uniform +W at the drain under-weighted DynSGD's last round).
    ``num_workers`` is kept in the signature for callers that partial
    it in alongside the round fn."""
    del num_workers  # true depth at the drain: no window ran ahead
    ordered = _take(pending, pending_perm)
    ps_state, _ = apply_commit_round_pulls(
        rule, ps_state, ordered, None, staleness_offset=0)
    return ps_state


def _fast_round(rule: UpdateRule, ps_state: PSState, payloads: Pytree,
                local_params: Pytree, inv: jnp.ndarray, num_workers: int):
    """Closed-form center update + deferred pulls (see module docstring)."""
    center = ps_state.center
    if isinstance(rule, ElasticRule):
        # center_W = (1-a)^W c0 + a * sum_w (1-a)^(W-1-pos_w) * x_w
        a = rule.alpha
        decay = (1.0 - a) ** num_workers
        w_coeff = a * (1.0 - a) ** (num_workers - 1.0
                                    - inv.astype(jnp.float32))
        new_center = jax.tree_util.tree_map(
            lambda c, x: decay * c + jnp.tensordot(w_coeff, x, axes=1),
            center, payloads)
        # Worker move against the round-start center (pull-timing approx).
        pulled = jax.vmap(
            lambda local, c: rule.worker_pull(local, c, c),
            in_axes=(0, None))(local_params, center)
    else:
        if isinstance(rule, DynSGDRule):
            scale = 1.0 / (inv.astype(jnp.float32) + 1.0)
        else:
            scale = jnp.ones((num_workers,), jnp.float32)
        new_center = jax.tree_util.tree_map(
            lambda c, p: c + jnp.tensordot(scale, p, axes=1),
            center, payloads)
        pulled = _broadcast_like(new_center, num_workers)
    new_ps = PSState(center=new_center,
                     clock=ps_state.clock + num_workers)
    return new_ps, pulled


def commit_permutation(rng: jax.Array, num_workers: int) -> jnp.ndarray:
    """Per-round commit order — the emulator's stand-in for the TCP race."""
    return jax.random.permutation(rng, num_workers)

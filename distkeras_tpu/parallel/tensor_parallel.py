"""Tensor parallelism: parameter sharding rules over the mesh ``model`` axis.

Beyond the reference (SURVEY.md §2.3: "Tensor parallelism: NO"), because
on TPU it is nearly free to express: pick a mesh, annotate the parameter
shardings, and XLA/GSPMD inserts the ICI collectives (the scaling-book
recipe).  There is no hand-written collective anywhere in this module —
a rule maps a parameter *path* to a ``PartitionSpec`` and everything else
is ``jax.device_put`` + ``jit``.

The rules are Megatron-style for the transformer: attention Q/K/V are
column-parallel over heads, the output projection is row-parallel, the
MLP is column- then row-parallel, and the LM head is column-parallel
over the vocabulary — so each block needs exactly one all-reduce in
forward and one in backward, which GSPMD derives on its own from these
annotations.

Optimizer state needs no extra rules: Adam's ``mu``/``nu`` mirror the
parameter tree, so their paths end in the same ``.../kernel`` suffixes
and the same rules match (``tree_shardings`` works on any pytree —
``TrainState`` included).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.mesh import MODEL_AXIS

# A rule is (path-regex, spec) where spec is a PartitionSpec or a
# callable (path_str, leaf) -> PartitionSpec.  First match wins; no
# match -> replicated.
Rule = tuple[str, Any]

TRANSFORMER_TP_RULES: Sequence[Rule] = (
    # Attention: Q/K/V column-parallel over heads [d_model, H, Dh].
    (r"(query|key|value)/kernel$", P(None, MODEL_AXIS, None)),
    (r"(query|key|value)/bias$", P(MODEL_AXIS, None)),
    # Output projection row-parallel [H, Dh, d_model]; bias replicated.
    (r"out/kernel$", P(MODEL_AXIS, None, None)),
    # Block MLP: column- then row-parallel.
    (r"Block_\d+/Dense_0/kernel$", P(None, MODEL_AXIS)),
    (r"Block_\d+/Dense_0/bias$", P(MODEL_AXIS)),
    (r"Block_\d+/Dense_1/kernel$", P(MODEL_AXIS, None)),
    # LM head column-parallel over the vocabulary.
    (r"lm_head/kernel$", P(None, MODEL_AXIS)),
    (r"lm_head/bias$", P(MODEL_AXIS)),
    # MoE FFN: experts sharded over the model axis (expert parallelism
    # via GSPMD — the dense-einsum MoEFFN's expert-dim batched matmuls
    # partition on E); router replicated (no rule).
    (r"moe/(w_in|w_out)$", P(MODEL_AXIS, None, None)),
    (r"moe/(b_in|b_out)$", P(MODEL_AXIS, None)),
)


def _alternating_dense(path: str, leaf) -> P:
    """Even Dense layers column-parallel, odd row-parallel, so each
    even/odd pair contracts with a single all-reduce and the elementwise
    activation between them runs on the sharded feature axis."""
    idx = int(re.search(r"Dense_(\d+)", path).group(1))
    if path.endswith("kernel"):
        return P(None, MODEL_AXIS) if idx % 2 == 0 else P(MODEL_AXIS, None)
    return P(MODEL_AXIS) if idx % 2 == 0 else P()


MLP_TP_RULES: Sequence[Rule] = (
    (r"Dense_\d+/(kernel|bias)$", _alternating_dense),
)

TP_RULES: dict[str, Sequence[Rule]] = {
    "transformer_lm": TRANSFORMER_TP_RULES,
    "mlp": MLP_TP_RULES,
}


def rules_for(family: str) -> Sequence[Rule]:
    """TP rules for a registered model family.

    Families without rules (convnet/resnet/bilstm/widedeep) are
    deliberately absent: their parameters are small enough that
    data-parallel replication is the right layout, and annotating them
    would only add collectives.
    """
    try:
        return TP_RULES[family]
    except KeyError:
        raise ValueError(
            f"no tensor-parallel rules for model family {family!r}; "
            f"available: {sorted(TP_RULES)}. Pass explicit rules, or "
            f"use model_parallel=1.") from None


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(getattr(entry, "name", entry)))
    return "/".join(parts)


def spec_for(path_str: str, leaf, rules: Sequence[Rule]) -> P:
    """The PartitionSpec the first matching rule assigns (else ``P()``)."""
    for pattern, spec in rules:
        if re.search(pattern, path_str):
            if callable(spec):
                spec = spec(path_str, leaf)
            ndim = getattr(leaf, "ndim", None)
            if ndim is not None and len(spec) > ndim:
                raise ValueError(
                    f"rule {pattern!r} assigns rank-{len(spec)} spec "
                    f"{spec} to rank-{ndim} leaf at {path_str!r}")
            return spec
    return P()


def tree_shardings(mesh: Mesh, tree,
                   rules: Sequence[Rule]) -> Any:
    """``NamedSharding`` for every leaf of ``tree`` (params, a whole
    ``TrainState``, optimizer state, ...), by path-matching ``rules``.
    Unmatched leaves are replicated."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, spec_for(_path_str(path), leaf, rules)),
        tree)


def stacked_tree_shardings(mesh: Mesh, tree, rules: Sequence[Rule],
                           axis_name: str | None = None) -> Any:
    """Shardings for a tree whose every leaf carries a leading stacked
    axis (e.g. vmapped per-worker ``TrainState``s, ``[W, ...]``): the
    stacked axis shards over ``axis_name`` (default: the mesh's worker
    axis) and the remaining dims follow the TP rules — the layout of
    tensor-parallel workers under the async PS family."""
    from distkeras_tpu.mesh import WORKER_AXIS

    axis = WORKER_AXIS if axis_name is None else axis_name

    def f(path, leaf):
        # rules (incl. callables and the rank guard) see the UNSTACKED
        # leaf, exactly as in the non-stacked path
        unstacked = jax.ShapeDtypeStruct(tuple(leaf.shape[1:]),
                                         getattr(leaf, "dtype", None))
        spec = spec_for(_path_str(path), unstacked, rules)
        return NamedSharding(mesh, P(axis, *spec))

    return jax.tree_util.tree_map_with_path(f, tree)


def shard_tree(mesh: Mesh, tree, rules: Sequence[Rule]):
    """Place ``tree`` on ``mesh`` with the rules' shardings (single
    ``jax.device_put`` per leaf; GSPMD handles everything downstream)."""
    return jax.device_put(tree, tree_shardings(mesh, tree, rules))

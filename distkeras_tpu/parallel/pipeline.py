"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

Beyond the reference (SURVEY.md §2.3: "Pipeline parallelism: NO"),
completing the parallelism set (dp / tp / sp / pp / ep) the TPU mesh
makes cheap to express.  Each device on the ``stage`` axis holds ONE
stage's parameters (a homogeneous stack sharded on its leading axis);
activations flow stage-to-stage over ICI with ``lax.ppermute``, one hop
per tick, while microbatches stream in behind each other — the classic
fill-drain (GPipe) schedule with bubble fraction
``(S-1) / (M + S - 1)`` for ``S`` stages and ``M`` microbatches.

This is an SPMD program: every device runs the same tick loop
(``lax.scan``), computing its stage on whatever microbatch currently
occupies it.  Differentiable — autodiff through ``ppermute`` reverses
the ring, so the backward pass is the same pipeline running backwards;
no custom VJP is needed.

Composition: the stage axis composes with the data-parallel axis in the
same mesh (see ``__graft_entry__._dryrun_pipeline_parallel``: a
``(workers, stage)`` mesh with the batch sharded over ``workers``).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array, *,
                   axis_name: str, num_microbatches: int) -> jax.Array:
    """Run ``x`` through S pipelined stages under ``shard_map``.

    Args:
      stage_fn: ``(params_one_stage, activation [mb, ...]) ->
        activation [mb, ...]`` — one stage's compute.  Activations must
        keep one shape across stages (homogeneous pipeline).
      stage_params: this device's slice of the stacked stage parameters
        (call under ``shard_map`` with the stack's leading axis sharded
        over ``axis_name``; the leading axis of each leaf here is 1 and
        is squeezed).
      x: this device's copy of the full local batch ``[B, ...]``;
        ``B`` must divide into ``num_microbatches``.
      axis_name: the mesh axis whose size is the number of stages.
      num_microbatches: GPipe microbatch count ``M``; larger M shrinks
        the bubble, smaller M shrinks activation working memory.

    Returns:
      ``[B, ...]`` outputs of the final stage, valid on EVERY device
      (the last stage's results are broadcast with ``psum`` so the
      caller can compute a loss without caring about stage placement).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[:1] != (1,):
            raise ValueError(
                f"stage_params leaves must arrive with a local leading "
                f"axis of 1 (one stage per device — shard the stack's "
                f"leading axis over {axis_name!r}); got shape "
                f"{leaf.shape} for a {n_stages}-stage pipeline")
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible into {num_microbatches} "
            f"microbatches")
    mb = b // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])

    n_ticks = num_microbatches + n_stages - 1
    # Ring: stage s sends its output forward to stage s+1 each tick.
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # Device-varying zeros from tick 0 (scan's carry typing must agree
    # with the computed, varying outputs).
    state0 = lax.pcast(jnp.zeros_like(micro[0]), (axis_name,),
                       to="varying")
    out0 = lax.pcast(jnp.zeros_like(micro), (axis_name,), to="varying")
    # The tick loop: stage 0 ingests microbatch t (while t < M), every
    # stage applies its compute, results hop one stage forward, and the
    # last stage banks microbatch t - (S-1) once the pipe has filled.

    def tick(carry, t):
        state, outs = carry
        feed = micro[jnp.minimum(t, num_microbatches - 1)]
        state = jnp.where(stage == 0, feed, state)
        y = stage_fn(params, state)
        done = t - (n_stages - 1)
        outs = jnp.where(
            (stage == n_stages - 1) & (done >= 0),
            outs.at[jnp.maximum(done, 0)].set(y), outs)
        state = lax.ppermute(y, axis_name, fwd)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, out0), jnp.arange(n_ticks))
    # Only the last stage holds real outputs; broadcast them.
    outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
    outs = lax.psum(outs, axis_name)
    return outs.reshape((b,) + outs.shape[2:])

"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

Beyond the reference (SURVEY.md §2.3: "Pipeline parallelism: NO"),
completing the parallelism set (dp / tp / sp / pp / ep) the TPU mesh
makes cheap to express.  Each device on the ``stage`` axis holds ONE
stage's parameters (a homogeneous stack sharded on its leading axis);
activations flow stage-to-stage over ICI with ``lax.ppermute``, one hop
per tick, while microbatches stream in behind each other — the classic
fill-drain (GPipe) schedule with bubble fraction
``(S-1) / (M + S - 1)`` for ``S`` stages and ``M`` microbatches.

This is an SPMD program: every device runs the same tick loop
(``lax.scan``), computing its stage on whatever microbatch currently
occupies it.  Differentiable — autodiff through ``ppermute`` reverses
the ring, so the backward pass is the same pipeline running backwards;
no custom VJP is needed.

Composition: the stage axis composes with the data-parallel axis in the
same mesh (see ``__graft_entry__._dryrun_pipeline_parallel``: a
``(workers, stage)`` mesh with the batch sharded over ``workers``).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from distkeras_tpu.utils import axis_size, pcast

STAGE_AXIS = "stage"


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array, *,
                   axis_name: str, num_microbatches: int) -> jax.Array:
    """Run ``x`` through S pipelined stages under ``shard_map``.

    Args:
      stage_fn: ``(params_one_stage, activation [mb, ...]) ->
        activation [mb, ...]`` — one stage's compute.  Activations must
        keep one shape across stages (homogeneous pipeline).
      stage_params: this device's slice of the stacked stage parameters
        (call under ``shard_map`` with the stack's leading axis sharded
        over ``axis_name``; the leading axis of each leaf here is 1 and
        is squeezed).
      x: this device's copy of the full local batch ``[B, ...]``;
        ``B`` must divide into ``num_microbatches``.
      axis_name: the mesh axis whose size is the number of stages.
      num_microbatches: GPipe microbatch count ``M``; larger M shrinks
        the bubble, smaller M shrinks activation working memory.

    Returns:
      ``[B, ...]`` outputs of the final stage, valid on EVERY device
      (the last stage's results are broadcast with ``psum`` so the
      caller can compute a loss without caring about stage placement).
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[:1] != (1,):
            raise ValueError(
                f"stage_params leaves must arrive with a local leading "
                f"axis of 1 (one stage per device — shard the stack's "
                f"leading axis over {axis_name!r}); got shape "
                f"{leaf.shape} for a {n_stages}-stage pipeline")
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible into {num_microbatches} "
            f"microbatches")
    mb = b // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])

    n_ticks = num_microbatches + n_stages - 1
    # Ring: stage s sends its output forward to stage s+1 each tick.
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # Device-varying zeros from tick 0 (scan's carry typing must agree
    # with the computed, varying outputs).
    state0 = pcast(jnp.zeros_like(micro[0]), (axis_name,),
                       to="varying")
    out0 = pcast(jnp.zeros_like(micro), (axis_name,), to="varying")
    # The tick loop: stage 0 ingests microbatch t (while t < M), every
    # stage applies its compute, results hop one stage forward, and the
    # last stage banks microbatch t - (S-1) once the pipe has filled.

    def tick(carry, t):
        state, outs = carry
        feed = micro[jnp.minimum(t, num_microbatches - 1)]
        state = jnp.where(stage == 0, feed, state)
        y = stage_fn(params, state)
        done = t - (n_stages - 1)
        outs = jnp.where(
            (stage == n_stages - 1) & (done >= 0),
            outs.at[jnp.maximum(done, 0)].set(y), outs)
        state = lax.ppermute(y, axis_name, fwd)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, out0), jnp.arange(n_ticks))
    # Only the last stage holds real outputs; broadcast them.
    outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
    outs = lax.psum(outs, axis_name)
    return outs.reshape((b,) + outs.shape[2:])


# ---------------------------------------------------------------------------
# Trainer surface: pipelined TransformerLM (VERDICT.md r2 Missing: "PP
# is an op, not a trainer")
# ---------------------------------------------------------------------------


def lm_state_specs(state):
    """PartitionSpec tree for a ``TrainState`` of a
    ``TransformerLM(scan_blocks=True)``: the layer stack (every leaf
    under a ``blocks`` key — optimizer moments mirror the params tree,
    so the rule catches those too) shards its leading (layer) axis over
    the ``stage`` mesh axis; everything else is replicated."""

    def spec_for(path, leaf):
        del leaf
        keys = {getattr(k, "key", getattr(k, "name", None))
                for k in path}
        return P(STAGE_AXIS) if "blocks" in keys else P()

    return jax.tree_util.tree_map_with_path(spec_for, state)


def make_pp_train_step(model, loss_fn, tx, mesh: Mesh, *,
                       num_microbatches: int,
                       workers_axis: str = "workers",
                       features_col: str = "features",
                       label_col: str = "label"):
    """Build a jitted ``step(state, batch) -> (state, metrics)`` that
    trains a ``TransformerLM(scan_blocks=True)`` dp x pp over
    ``mesh = (workers, stage)``.

    Per-device SPMD under ``shard_map``: every device embeds its local
    batch rows (replicated compute along ``stage``), the layer stack —
    sharded ``num_layers/S`` layers per stage — runs through
    ``pipeline_apply``'s GPipe schedule, and the final norm/head/loss
    are computed identically on every stage device from the
    psum-broadcast pipeline output.  Gradient reductions follow the
    replication structure: everything pmean-s over ``workers`` (data
    parallelism); the pre-pipeline embeddings additionally psum over
    ``stage`` (their cotangent lands only on stage 0, which ingests the
    microbatches); the layer stack and the post-pipeline norm/head need
    no stage reduction (stage-local and stage-identical respectively).
    """
    from distkeras_tpu.models.transformer import Block

    cfg = model
    dtype = jnp.dtype(cfg.dtype)

    def forward(params, tokens):
        import flax.linen as nn

        tokens = tokens.astype(jnp.int32)
        t = tokens.shape[1]
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=dtype).apply(
            {"params": params["Embed_0"]}, tokens)
        pos = nn.Embed(cfg.max_len, cfg.d_model, dtype=dtype).apply(
            {"params": params["pos_embed"]},
            jnp.arange(t)[None, :])
        x = x + pos

        def stage_fn(stage_stack, h):
            def body(carry, layer_params):
                out = Block(cfg.num_heads, cfg.mlp_ratio, dtype).apply(
                    {"params": layer_params}, carry)
                return out, None
            h, _ = lax.scan(body, h, stage_stack)
            return h

        # local stack: [L/S, ...] -> leading 1 (pipeline_apply's
        # one-stage-per-device contract)
        stack = jax.tree_util.tree_map(lambda p: p[None],
                                       params["blocks"]["layer"])
        x = pipeline_apply(stage_fn, stack, x, axis_name=STAGE_AXIS,
                           num_microbatches=num_microbatches)
        x = nn.LayerNorm(dtype=dtype).apply(
            {"params": params["LayerNorm_0"]}, x)
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32).apply(
            {"params": params["lm_head"]}, x)

    def per_device_step(state, batch):
        tokens, labels = batch[features_col], batch[label_col]

        def objective(params):
            logits = forward(params, tokens)
            return loss_fn(logits, labels)

        loss, grads = jax.value_and_grad(objective)(state.params)
        loss = lax.pmean(loss, workers_axis)

        def reduce(path, g):
            keys = {getattr(k, "key", getattr(k, "name", None))
                    for k in path}
            g = lax.pmean(g, workers_axis)
            if keys & {"Embed_0", "pos_embed"}:
                # cotangent lives only on stage 0 (the ingesting
                # stage); collect it so every replica updates alike
                g = lax.psum(g, STAGE_AXIS)
            return g

        grads = jax.tree_util.tree_map_with_path(reduce, grads)
        import optax

        updates, new_opt_state = tx.update(grads, state.opt_state,
                                           state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(step=state.step + 1,
                                  params=new_params,
                                  opt_state=new_opt_state)
        return new_state, {"loss": loss}

    def step(state, batch):
        from distkeras_tpu.utils import shard_map

        specs = lm_state_specs(state)
        batch_specs = {k: P(workers_axis) for k in batch}
        return shard_map(
            per_device_step, mesh=mesh,
            in_specs=(specs, batch_specs),
            out_specs=(specs, P()))(state, batch)

    return step

"""Replicated parameter server: hot standby, automatic failover, and
epoch fencing (ISSUE 10 tentpole).

The training PS was the last single point of failure: snapshots +
``PSServer.restart_from`` recover state but need an OPERATOR to bring
a server back, while the serving tier already fails over by itself
(``gateway``).  This module closes that gap with primary/standby
replication in the spirit of Li et al.'s parameter-server replication
and the bounded-staleness recovery argument of SSP/Petuum:

* **Log shipping.**  The primary ships its commit log — seq-ordered
  applied payloads plus dedupe-table updates, per-shard for the
  sharded server — to N standbys over ``WIRE_OPS``-registered opcodes
  on the existing ``transport`` framing (scope ``"repl"``: requests
  ``a``/``h``/``?``/``b``, replies ``k``/``f``/``g``).  Each entry
  carries the payload bytes, the staleness the primary derived, and
  the primary's packed reply, so a standby's replay reconstructs the
  center, the clocks AND the commit-seq dedupe table byte-identically
  — which is what makes a client retry across the failover boundary
  exactly-once.
* **Sync / async ack.**  ``mode="sync"`` ships from inside the commit
  lock: a commit's reply cannot escape to the worker before every
  reachable standby acked it.  ``mode="async"`` appends and lets the
  shipper thread drain — lower commit latency, but a primary crash can
  lose the unshipped tail (the client's retry re-applies it on the
  promoted standby; still at-most-once, no longer exactly-once).
  Standby lag is surfaced as the ``ps_standby_lag`` gauge and flagged
  as a ``ps_replica_lag`` flight event when it crosses ``max_lag``.
* **Epoch fencing.**  Every promotion mints a fencing epoch stamped on
  the replication wire.  Epochs are GLOBALLY unique: each node mints
  the smallest value above its current epoch in its own residue class
  (``epoch % N == index``), so standbys electing concurrently on both
  sides of a partition can never arrive at the same epoch — one of the
  two is always strictly newer and fences the other.  A standby
  rejects log entries below its epoch with the ``f`` reply (a primary
  also rejects entries AT its epoch — a second same-epoch writer is a
  protocol violation); a deposed primary that comes back is fenced —
  its commits raise ``PSFencedError`` instead of splitting the brain —
  and is later re-absorbed as a standby via a full bootstrap.  Append
  and heartbeat frames also carry the primary's promotion ``base``
  (the seq it promoted at): a standby whose ``last_applied`` exceeds
  the base of a newer-epoch primary holds old-epoch entries the new
  primary will rewrite, so it demands a full resync instead of acking
  those seqs as duplicates and silently diverging.
* **Deterministic promotion, with quorum.**  A standby that loses
  contact with the primary for ``failover_timeout`` probes every peer
  before declaring it dead (mirroring ``gateway.RemoteReplica.probe``)
  and only elects when a MAJORITY of the group is accounted for —
  answered the probe, or confirmed dead by the host actively refusing
  the connection.  An isolated standby's probes time out instead, so
  it refuses to usurp a primary it merely cannot see.  The winner is
  the highest ``(epoch, last_applied_seq)`` with ties broken by
  address order (``elect`` — a pure function every replica evaluates
  identically) and starts serving workers on its pre-reserved,
  advertised port — no operator action.

``ResilientPSClient.for_replicas`` (``host_ps``) is the worker-side
arm: an ordered replica list walked with probe-before-declare-dead, so
training continues through a primary kill with the retried commit
deduped on the promoted standby.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Any, Optional, Sequence

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.host_ps import (
    _NO_SEQ,
    _to_numpy,
    HostParameterServer,
    PSFencedError,
    PSServer,
)
from distkeras_tpu.parallel.update_rules import UpdateRule

Pytree = Any

#: gap-reply sentinel: "my state cannot chain onto your log — send a
#: full bootstrap" (log seqs start at 1, so 0 is never a real position)
_BOOTSTRAP_ME = 0


def elect(candidates: Sequence[tuple[int, int, int]]) -> int:
    """Deterministic promotion: each candidate is ``(epoch,
    last_applied_seq, address_index)``; the highest ``(epoch,
    last_applied_seq)`` wins, ties broken by ADDRESS ORDER (the lowest
    index).  Every replica evaluates the same pure function over
    whatever candidate set it can reach, so concurrent elections over
    the same reachable set agree — and disagreement (a partition)
    resolves by epoch fencing, not by both winners serving."""
    if not candidates:
        raise ValueError("election needs at least one candidate")
    best = max(candidates,
               key=lambda c: (int(c[0]), int(c[1]), -int(c[2])))
    return int(best[2])


def mint_epoch(current: int, floor: int, index: int,
               group: int) -> int:
    """Pure residue-class epoch mint: the smallest value strictly above
    ``max(current, floor)`` with ``epoch % group == index``.  Epochs
    are therefore globally unique across the group — two nodes can
    never mint the same value, so equal-epoch split brain is
    structurally impossible (``promote`` uses this; the protocol model
    in ``analysis.protomodel`` imports it rather than re-deriving)."""
    n = max(int(group), 1)
    epoch = max(int(current), int(floor)) + 1
    epoch += (int(index) - epoch) % n
    return epoch


def probe_replica(addr: tuple[str, int], timeout: float = 0.5
                  ) -> tuple[Optional[dict], bool]:
    """``query_status`` plus the failure mode: ``(status,
    confirmed_down)``.  ``confirmed_down`` is True only when the
    peer's host actively REFUSED the connection — its kernel answered
    but no process listens, i.e. a crash or a closed socket.  That is
    evidence of death a silent timeout (a partition) is not, so
    elections count refused peers toward quorum while timed-out peers
    stay unaccounted."""
    try:
        sock = transport.connect(addr[0], addr[1], timeout=timeout)
    except ConnectionRefusedError:
        return None, True
    except OSError:
        return None, False
    try:
        sock.settimeout(timeout)
        transport.send_msg(sock, b"?")
        obj = transport.unpack_obj(transport.recv_msg(sock))
        return {"epoch": int(obj["epoch"]),
                "last_applied": int(obj["last_applied"]),
                "role": str(obj["role"]),
                "index": int(obj.get("index", -1))}, False
    except (OSError, ValueError, KeyError):
        return None, False
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _ps_from_snapshot(rule: UpdateRule, snapshot: dict, *,
                      snapshot_path=None, snapshot_every: int = 0):
    """Restore the right server class from a snapshot dict (the same
    ``"sharded"``-key dispatch as ``PSServer.restart_from``, minus the
    server start)."""
    if "sharded" in snapshot:
        from distkeras_tpu.parallel.sharded_ps import (
            ShardedParameterServer)

        return ShardedParameterServer.from_snapshot(
            rule, snapshot, snapshot_path=snapshot_path,
            snapshot_every=snapshot_every)
    return HostParameterServer.from_snapshot(
        rule, snapshot, snapshot_path=snapshot_path,
        snapshot_every=snapshot_every)


def query_status(addr: tuple[str, int],
                 timeout: float = 0.5) -> Optional[dict]:
    """One replica's replication status via the ``?`` wire verb —
    ``{"epoch", "last_applied", "role", "index"}`` — or ``None`` if the
    replica is unreachable.  The operator's peek; the election uses
    ``probe_replica``, which also reports HOW the probe failed."""
    return probe_replica(addr, timeout=timeout)[0]


class _Link:
    """One standby's shipping state, owned by the replicator lock."""

    __slots__ = ("addr", "sock", "acked", "alive", "needs_bootstrap",
                 "last_error")

    def __init__(self, addr: tuple[str, int], acked: int):
        self.addr = (str(addr[0]), int(addr[1]))
        self.sock: Optional[socket.socket] = None
        self.acked = int(acked)
        self.alive = True  # optimistic; first failed ship downs it
        self.needs_bootstrap = False
        self.last_error: Optional[str] = None


class Replicator:
    """Primary-side commit-log shipper.

    ``replicate(**entry)`` is called by the parameter server from
    INSIDE its commit lock (``HostParameterServer.commit`` /
    ``ShardedParameterServer.commit_shard``): the entry is appended to
    the bounded in-memory log under the replicator lock and — in sync
    mode — shipped to every live standby before the call returns, so
    an acked commit is already replicated.  A standby replying
    ``fenced`` (it saw a higher epoch) raises ``PSFencedError`` out of
    the commit: the deposed primary refuses the commit rather than
    split the brain; the node monitor sees ``.fenced`` and demotes.

    A maintenance thread (``start()``) heartbeats idle standbys,
    revives downed links, drains the async backlog, and
    full-bootstraps standbys that cannot chain onto the bounded log
    (consistent snapshot + resubscribe).  Lock order everywhere: PS
    commit lock (when held) -> replicator lock; the bootstrap path
    takes the PS lock first (``ps.replication_snapshot``) and only
    then the replicator lock — never the reverse.
    """

    def __init__(self, ps, standbys: Sequence[tuple[str, int]], *,
                 epoch: int, mode: str = "sync", start_seq: int = 1,
                 ack_timeout: float = 5.0, heartbeat_s: float = 0.25,
                 max_lag: int = 64, max_log: int = 1024):
        if mode not in ("sync", "async"):
            raise ValueError(
                f"mode must be 'sync' or 'async', got {mode!r}")
        self._ps = ps
        self.epoch = int(epoch)
        self.mode = mode
        self.ack_timeout = float(ack_timeout)
        self.heartbeat_s = float(heartbeat_s)
        self.max_lag = int(max_lag)
        self.max_log = int(max_log)
        self.fenced = False  # read lock-free by the node monitor
        self.newer_epoch = int(epoch)
        #: this primary's promotion point: every log seq above it is a
        #: THIS-epoch entry.  Stamped on append/heartbeat frames so a
        #: standby whose position exceeds it knows its tail belongs to
        #: an older epoch and demands a resync instead of acking.
        self.base = int(start_seq) - 1
        self._lock = racecheck.lock("replicated_ps.replicator")
        self._next_seq = int(start_seq)  # guarded-by: _lock
        self._log: list[tuple[int, bytes]] = []  # guarded-by: _lock
        self._links = [_Link(a, start_seq - 1) for a in standbys]
        self._lag_flagged = False  # guarded-by: _lock
        self._unreplicated_flagged = False  # guarded-by: _lock
        self._wake = threading.Event()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the hot path (called under the PS commit lock) ----------------

    def replicate(self, **entry) -> None:
        """Append one commit-log entry and (sync mode) ship it.  Raises
        ``PSFencedError`` if this primary has been deposed — the
        caller's commit must fail, not ack."""
        data = transport.pack_obj(dict(entry))
        with telemetry.span("ps_replicate", mode=self.mode), \
                self._lock:
            if self.fenced:
                raise self._fenced_error()
            seq = self._next_seq
            self._next_seq += 1
            self._log.append((seq, data))
            if len(self._log) > self.max_log:
                del self._log[:len(self._log) - self.max_log]
            telemetry.metrics().counter(
                "ps_replicated_entries_total").inc()
            if self.mode == "sync":
                self._ship_all_locked()
                self._flag_unreplicated_locked(seq)
            self._update_lag_locked()
        self._wake.set()

    def _flag_unreplicated_locked(self, seq: int) -> None:
        """Sync mode promises an acked commit is already on a standby;
        when every standby is down that promise silently lapses (the
        commit still acks — halting training on a lone survivor would
        be worse).  Make the lapse LOUD instead of silent: count every
        such commit and flight-record the edge, so a later bootstrap
        rewind that loses them is attributable."""
        if not self._links:
            return  # replicas=1: no standbys were ever promised
        if any(link.acked >= seq for link in self._links):
            self._unreplicated_flagged = False
            return
        telemetry.metrics().counter(
            "ps_sync_unreplicated_total").inc()
        if not self._unreplicated_flagged:
            self._unreplicated_flagged = True
            # blocking by design: edge-triggered
            # (once per outage) — the guarantee lapse must reach the
            # flight log before more unreplicated commits ack
            flight_recorder.record("ps_sync_unreplicated",
                                   seq=int(seq), epoch=self.epoch)

    def head_seq(self) -> int:
        """The last assigned log seq.  A caller holding the PS commit
        lock(s) (``replication_snapshot``) reads a value exactly
        consistent with the PS state: every entry is assigned under
        that lock."""
        with self._lock:
            return self._next_seq - 1

    def acked_seqs(self) -> dict[tuple[str, int], int]:
        """Per-standby last acked log seq (chaos drills assert the
        promoted standby acked everything the dead primary acked)."""
        with self._lock:
            return {link.addr: int(link.acked)
                    for link in self._links}

    # -- shipping (all under self._lock) -------------------------------

    def _fenced_error(self) -> PSFencedError:
        err = PSFencedError(
            f"primary at epoch {self.epoch} fenced: a standby holds "
            f"epoch {self.newer_epoch}")
        err.newer_epoch = self.newer_epoch
        return err

    def _fence_locked(self, their_epoch: int) -> PSFencedError:
        self.fenced = True
        self.newer_epoch = max(self.newer_epoch, int(their_epoch))
        telemetry.metrics().counter("ps_fenced_total").inc()
        # blocking by design: the fencing decision
        # must hit the flight log before any caller observes it — this
        # is the split-brain postmortem's key event
        flight_recorder.record("ps_fenced", role="primary",
                               epoch=self.epoch,
                               newer_epoch=int(their_epoch))
        flight_recorder.flush()
        return self._fenced_error()

    def _log_entry_locked(self, seq: int) -> Optional[bytes]:
        if not self._log or seq < self._log[0][0]:
            return None
        data_seq, data = self._log[seq - self._log[0][0]]
        if data_seq != seq:  # defensive: the log must be contiguous
            raise AssertionError(
                f"replication log skew: wanted {seq}, found "
                f"{data_seq}")
        return data

    def _ensure_sock_locked(self, link: _Link) -> None:
        if link.sock is None:
            # blocking by design: sync ack mode —
            # the commit's reply must not escape before the standbys
            # ack, so the ship (connect included) happens under the
            # lock by design; ack_timeout bounds the stall
            link.sock = transport.connect(
                link.addr[0], link.addr[1], timeout=self.ack_timeout)
            link.sock.settimeout(self.ack_timeout)

    def _mark_down_locked(self, link: _Link, exc: Exception) -> None:
        link.alive = False
        link.last_error = repr(exc)
        if link.sock is not None:
            try:
                link.sock.close()
            except OSError:
                pass
            link.sock = None
        telemetry.metrics().counter("ps_standby_down_total").inc()

    def _handle_reply_locked(self, link: _Link, reply: bytes) -> None:
        tag, value = bytes(reply[:1]), int.from_bytes(reply[1:9],
                                                      "big")
        if tag == b"k":
            link.acked = max(link.acked, value)
        elif tag == b"f":
            raise self._fence_locked(value)
        elif tag == b"g":
            head = self._next_seq - 1
            log_start = (self._log[0][0] if self._log
                         else self._next_seq)
            if (value == _BOOTSTRAP_ME or value > head + 1
                    or value < log_start):
                # the standby cannot chain onto our log (diverged,
                # ahead of us, or behind the bounded window): full
                # snapshot next maintenance tick
                link.needs_bootstrap = True
            else:
                link.acked = value - 1
        else:
            raise ConnectionError(f"bad replication ack {tag!r}")

    def _service_link_locked(self, link: _Link,
                             heartbeat: bool) -> None:
        """Ship every pending entry to one standby, then (optionally)
        a heartbeat; any wire failure downs the link."""
        try:
            self._ensure_sock_locked(link)
            guard = 0
            while link.acked < self._next_seq - 1 \
                    and not link.needs_bootstrap:
                seq = link.acked + 1
                data = self._log_entry_locked(seq)
                if data is None:
                    link.needs_bootstrap = True
                    break
                # blocking by design: sync ack mode
                # ships inside the commit lock by design (see
                # _ensure_sock_locked); ack_timeout bounds the stall
                transport.send_msg(
                    link.sock,
                    b"a" + self.epoch.to_bytes(8, "big")
                    + seq.to_bytes(8, "big")
                    + self.base.to_bytes(8, "big"), data)
                # blocking by design: same contract
                reply = transport.recv_msg(link.sock)
                self._handle_reply_locked(link, reply)
                guard += 1
                if guard > 2 * self.max_log:  # repeated gap replies
                    raise ConnectionError(
                        "standby not converging (gap loop)")
            if heartbeat and not link.needs_bootstrap:
                head = self._next_seq - 1
                # blocking by design: heartbeat on
                # the maintenance thread; ack_timeout bounds the stall
                transport.send_msg(
                    link.sock,
                    b"h" + self.epoch.to_bytes(8, "big")
                    + head.to_bytes(8, "big")
                    + self.base.to_bytes(8, "big"))
                # blocking by design: same contract
                reply = transport.recv_msg(link.sock)
                self._handle_reply_locked(link, reply)
        except PSFencedError:
            raise
        except (OSError, ValueError, ConnectionError) as e:
            self._mark_down_locked(link, e)

    def _ship_all_locked(self) -> None:
        for link in self._links:
            if link.alive and not link.needs_bootstrap:
                self._service_link_locked(link, heartbeat=False)

    def _update_lag_locked(self) -> None:
        head = self._next_seq - 1
        lag = head - min((link.acked for link in self._links),
                         default=head)
        telemetry.metrics().gauge("ps_standby_lag").set(lag)
        if lag > self.max_lag and not self._lag_flagged:
            self._lag_flagged = True
            # blocking by design: edge-triggered
            # (once per breach) — the lag breach must reach the flight
            # log even if the primary dies right after
            flight_recorder.record("ps_replica_lag", lag=int(lag),
                                   head=int(head),
                                   max_lag=self.max_lag)
        elif lag <= self.max_lag // 2:
            self._lag_flagged = False

    # -- maintenance thread --------------------------------------------

    def start(self) -> "Replicator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._maintain_loop,
                name="ps-replicator", daemon=True)
            self._thread.start()
        return self

    def _maintain_loop(self) -> None:
        while not self._stop_evt.is_set():
            self._wake.wait(self.heartbeat_s)
            self._wake.clear()
            if self._stop_evt.is_set() or self.fenced:
                return
            try:
                self._tick()
            except PSFencedError:
                return  # the node monitor sees .fenced and demotes
            except Exception:
                continue  # a sick standby must not kill maintenance

    def _tick(self) -> None:
        # bootstraps first, OUTSIDE the replicator lock: the snapshot
        # takes the PS lock, and lock order is PS -> replicator
        with self._lock:
            need = [link for link in self._links
                    if link.needs_bootstrap]
        for link in need:
            self._bootstrap_link(link)
        with self._lock:
            for link in self._links:
                if not link.alive:
                    # optimistic revive: the next ship either works or
                    # downs it again; position is re-learned from the
                    # standby's gap/ack replies, so a standby that came
                    # back on its own schedule just catches up
                    link.alive = True
                    self._service_link_locked(link, heartbeat=True)
                elif not link.needs_bootstrap:
                    self._service_link_locked(link, heartbeat=True)
            self._update_lag_locked()

    def _bootstrap_link(self, link: _Link) -> None:
        """Full-state resync of one standby: a consistent (log head,
        snapshot) pair from the PS — read under the PS commit lock(s),
        where no commit can interleave between the state copy and the
        head read — shipped as one ``b`` frame."""
        head, snap = self._ps.replication_snapshot(self.head_seq)
        data = transport.pack_obj(snap)
        with self._lock:
            if self.fenced:
                return
            try:
                self._ensure_sock_locked(link)
                # lint: allow(blocking-call-under-lock): bootstrap is
                # rare (standby restart) and bounded by ack_timeout
                transport.send_msg(
                    link.sock,
                    b"b" + self.epoch.to_bytes(8, "big")
                    + head.to_bytes(8, "big"), data)
                # lint: allow(blocking-call-under-lock): same contract
                reply = transport.recv_msg(link.sock)
                self._handle_reply_locked(link, reply)
                link.needs_bootstrap = False
                link.alive = True
                telemetry.metrics().counter(
                    "ps_standby_bootstraps_total").inc()
            except PSFencedError:
                raise
            except (OSError, ValueError, ConnectionError) as e:
                self._mark_down_locked(link, e)

    def stop(self) -> None:
        self._stop_evt.set()
        self._wake.set()
        with self._lock:
            for link in self._links:
                if link.sock is not None:
                    try:
                        link.sock.close()
                    except OSError:
                        pass
                    link.sock = None


class PSReplica:
    """One replica of a replicated training PS — a SYMMETRIC node:
    every replica runs the replication listener, holds an inner
    parameter server (``HostParameterServer``, or
    ``ShardedParameterServer`` when ``num_shards > 1``) and RESERVES
    its advertised worker port (bound but not listening, so worker
    connects are refused until promotion).  The current primary
    additionally runs a worker-facing ``PSServer`` on that reserved
    socket plus a ``Replicator``; standbys replay the shipped log and
    watch the primary's heartbeats, electing a successor (``elect``)
    when it goes quiet.

    Roles are dynamic: promotion bumps the fencing epoch
    (``ps_promote`` flight event, ``ps_promotions_total`` counter); a
    deposed primary demotes in place (``ps_fenced``), its state rewound
    by a full bootstrap from the new primary before it rejoins the
    standby set.
    """

    def __init__(self, rule: UpdateRule, center: Pytree, *,
                 num_shards: int = 1, host: str = "127.0.0.1",
                 worker_port: int = 0, repl_port: int = 0,
                 snapshot_path: str | os.PathLike | None = None,
                 snapshot_every: int = 0, mode: str = "sync",
                 ack_timeout: float = 5.0, max_lag: int = 64,
                 failover_timeout: float = 1.0,
                 heartbeat_s: float | None = None,
                 probe_timeout: float = 0.25):
        """``failover_timeout`` is the standby's silence threshold
        before it opens an election; ``heartbeat_s`` (default a quarter
        of it — it must be well under) paces the primary's idle
        heartbeats, so a healthy-but-idle primary is never deposed.
        ``mode``/``ack_timeout``/``max_lag`` parameterize the
        ``Replicator`` this node builds when promoted."""
        if heartbeat_s is None:
            heartbeat_s = float(failover_timeout) / 4.0
        if heartbeat_s >= failover_timeout:
            raise ValueError(
                f"heartbeat_s={heartbeat_s} must be < "
                f"failover_timeout={failover_timeout} (a healthy "
                f"primary must heartbeat faster than standbys give "
                f"up on it)")
        self.rule = rule
        self._template = _to_numpy(center)
        self.num_shards = int(num_shards)
        self._snapshot_path = snapshot_path
        self._snapshot_every = int(snapshot_every)
        self.mode = mode
        self.ack_timeout = float(ack_timeout)
        self.max_lag = int(max_lag)
        self.failover_timeout = float(failover_timeout)
        self.heartbeat_s = float(heartbeat_s)
        self.probe_timeout = float(probe_timeout)
        self.ps = self._build_ps(center)
        # reserve the ADVERTISED worker port now: bound but not
        # listening, so a worker's connect is refused (not hung) until
        # this node is promoted and hands the socket to a PSServer
        self._worker_sock = self._bind(host, worker_port)
        self.worker_address = self._worker_sock.getsockname()
        self._repl_sock = self._bind(host, repl_port)
        self._repl_sock.listen()
        self.repl_address = self._repl_sock.getsockname()
        self._lock = racecheck.lock("replicated_ps.node")
        self.role = "standby"  # guarded-by: _lock
        self.index = 0  # position in the shared address order
        self.peers: list[dict] = []  # guarded-by: _lock
        self.last_applied = 0  # guarded-by: _lock
        self._diverged = False  # guarded-by: _lock (ex-primary state)
        self._last_contact = telemetry.now()  # guarded-by: _lock
        self.server: Optional[PSServer] = None  # guarded-by: _lock
        self.replicator: Optional[Replicator] = None  # guarded-by: _lock
        self._stop = threading.Event()
        self._started = False
        self._repl_conns: list[socket.socket] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ps-repl-accept",
            daemon=True)
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="ps-repl-monitor",
            daemon=True)

    def _build_ps(self, center: Pytree):
        if self.num_shards > 1:
            from distkeras_tpu.parallel.sharded_ps import (
                ShardedParameterServer)

            return ShardedParameterServer(
                self.rule, center, self.num_shards,
                snapshot_path=self._snapshot_path,
                snapshot_every=self._snapshot_every)
        return HostParameterServer(
            self.rule, center, snapshot_path=self._snapshot_path,
            snapshot_every=self._snapshot_every)

    @staticmethod
    def _bind(host: str, port: int) -> socket.socket:
        sock = socket.socket()
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        return sock

    @property
    def epoch(self) -> int:
        return int(self.ps.epoch)

    def set_peers(self, specs: Sequence[dict], index: int) -> None:
        """Install the group's shared, ORDERED address list (every
        replica holds the identical list — the order is the election
        tie-break) and this node's position in it.  Each spec is
        ``{"worker": (host, port), "repl": (host, port)}``."""
        peers = [{"worker": (str(s["worker"][0]),
                             int(s["worker"][1])),
                  "repl": (str(s["repl"][0]), int(s["repl"][1]))}
                 for s in specs]
        with self._lock:
            self.peers = peers
            self.index = int(index)

    def start(self) -> "PSReplica":
        if not self._started:
            self._started = True
            self._accept_thread.start()
            self._monitor_thread.start()
        return self

    # -- promotion / demotion ------------------------------------------

    def promote(self, reason: str = "manual",
                floor: int = 0) -> "PSReplica":
        """Become the primary: mint a fencing epoch, start the
        worker-facing ``PSServer`` on the reserved advertised port and
        a ``Replicator`` to every peer.  Idempotent while primary.

        The mint takes the smallest value above ``max(current epoch,
        floor)`` in THIS node's residue class (``epoch % N ==
        index``), so epochs are globally unique: standbys electing
        concurrently on both sides of a partition can never arrive at
        the same epoch — equal-epoch split brain is structurally
        impossible, and the strictly newer epoch always fences the
        other winner.  ``floor`` lets an election pass in the highest
        epoch it OBSERVED, so the winner's mint also dominates peers
        it is ahead of only by hearsay."""
        with self._lock:
            if self.role == "primary" or self._stop.is_set():
                return self
            new_epoch = mint_epoch(int(self.ps.epoch), int(floor),
                                   int(self.index), len(self.peers))
            self.ps.epoch = new_epoch
            self.ps._fenced = False
            self._diverged = False
            self.role = "primary"
            self._ensure_worker_sock_locked()
            standbys = [p["repl"] for i, p in enumerate(self.peers)
                        if i != self.index]
            repl = Replicator(
                self.ps, standbys, epoch=new_epoch, mode=self.mode,
                start_seq=int(self.last_applied) + 1,
                ack_timeout=self.ack_timeout,
                heartbeat_s=self.heartbeat_s, max_lag=self.max_lag)
            self.replicator = repl
            self.ps.attach_replicator(repl)
            self.server = PSServer(self.ps, self._template,
                                   sock=self._worker_sock).start()
            last = int(self.last_applied)
        telemetry.metrics().counter("ps_promotions_total").inc()
        flight_recorder.record(
            "ps_promote", epoch=new_epoch, last_applied=last,
            port=int(self.worker_address[1]), reason=str(reason))
        flight_recorder.flush(fsync=True)
        repl.start()
        return self

    def _ensure_worker_sock_locked(self) -> None:
        if self._worker_sock.fileno() == -1:  # closed by a demotion
            self._worker_sock = self._bind(self.worker_address[0],
                                           self.worker_address[1])

    def _adopt_epoch_locked(self, epoch: int, post: list) -> None:
        """A newer primary exists (higher epoch on the wire): adopt it
        and — if this node believed itself primary — demote.  The
        deposed node's state may hold commits the new primary never
        saw, so it is marked diverged: every append gets the
        bootstrap-me gap reply until a full resync rewinds it."""
        self.ps.epoch = int(epoch)
        if self.role == "primary":
            self.role = "standby"
            self._diverged = True
            server, self.server = self.server, None
            repl, self.replicator = self.replicator, None
            post.append(lambda: self._finish_demotion(
                server, repl, int(epoch)))
        self._last_contact = telemetry.now()

    def _finish_demotion(self, server, repl, epoch: int) -> None:
        """Demotion's slow half, OUTSIDE the node lock: fence the inner
        PS (in-flight worker commits raise ``PSFencedError``), tear
        down the worker server and the shipper, and re-reserve the
        advertised worker port for a future re-promotion."""
        self.ps.fence(epoch)
        if repl is not None:
            repl.stop()
        if server is not None:
            server.stop()
            with self._lock:
                try:
                    self._ensure_worker_sock_locked()
                except OSError:
                    pass  # port briefly busy; re-promotion retries
        flight_recorder.record("ps_fenced", role="demoted",
                               epoch=int(epoch),
                               port=int(self.worker_address[1]))
        flight_recorder.flush()

    # -- replication listener (always on) ------------------------------

    def _accept_loop(self) -> None:
        try:
            # inside the try: kill() may close the socket before this
            # thread gets scheduled, and that race must not traceback
            try:
                self._repl_sock.settimeout(0.2)
            except OSError:
                return
            while not self._stop.is_set():
                try:
                    conn, _ = self._repl_sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                conn.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                self._repl_conns.append(conn)
                threading.Thread(target=self._serve_repl,
                                 args=(conn,), daemon=True).start()
        finally:
            try:
                self._repl_sock.close()
            except OSError:
                pass

    def _serve_repl(self, conn: socket.socket) -> None:
        with conn:
            try:
                while not self._stop.is_set():
                    msg = transport.recv_msg_into(conn)
                    reply, post = self._dispatch_repl(msg)
                    transport.send_msg(conn, reply)
                    for fn in post:
                        fn()
            except (ConnectionError, OSError, ValueError):
                return

    def _dispatch_repl(self, msg) -> tuple[bytes, list]:
        cmd = bytes(msg[:1])
        if cmd == b"?":
            with self._lock:
                obj = {"epoch": int(self.ps.epoch),
                       "last_applied": int(self.last_applied),
                       "role": self.role, "index": int(self.index)}
            return transport.pack_obj(obj), []
        epoch = int.from_bytes(msg[1:9], "big")
        seq = int.from_bytes(msg[9:17], "big")
        if cmd == b"a":
            base = int.from_bytes(msg[17:25], "big")
            return self._append(epoch, seq, base, msg[25:])
        if cmd == b"h":
            base = int.from_bytes(msg[17:25], "big")
            return self._heartbeat(epoch, seq, base)
        if cmd == b"b":
            return self._bootstrap(epoch, seq, msg[17:])
        raise ValueError(f"unknown replication command {cmd!r}")

    def _gate_epoch_locked(self, epoch: int, post: list,
                           base: Optional[int] = None
                           ) -> Optional[bytes]:
        """Common epoch check: fence a stale primary (reply ``f``),
        adopt a newer epoch (demoting if needed), stamp liveness.
        Returns the fence reply, or ``None`` to proceed.

        Equal epoch while THIS node is primary is also fenced: epochs
        are minted in per-node residue classes, so a second primary at
        our epoch is a protocol violation — refuse its stream rather
        than apply a second writer's entries.

        ``base`` (append/heartbeat frames) is the sender's promotion
        point.  When adopting a newer epoch, a standby positioned
        BEYOND that base holds old-epoch entries the new primary will
        rewrite under its own epoch; acking them as duplicates would
        fast-forward the primary past entries it never shipped here,
        so the standby marks itself diverged and demands a resync."""
        my = int(self.ps.epoch)
        if epoch < my or (epoch == my and self.role == "primary"):
            post.append(
                lambda: self._record_fence_reject(epoch, my))
            return b"f" + my.to_bytes(8, "big")
        if epoch > my:
            self._adopt_epoch_locked(epoch, post)
            if base is not None and int(self.last_applied) > int(base):
                self._diverged = True
        self._last_contact = telemetry.now()
        return None

    def _record_fence_reject(self, their_epoch: int,
                             my_epoch: int) -> None:
        telemetry.metrics().counter("ps_fenced_total").inc()
        flight_recorder.record("ps_fenced", role="standby",
                               epoch=int(my_epoch),
                               stale_epoch=int(their_epoch))

    def _append(self, epoch: int, seq: int, base: int,
                data) -> tuple[bytes, list]:
        post: list = []
        entry = transport.unpack_obj(data)
        with self._lock:
            fence = self._gate_epoch_locked(epoch, post, base=base)
            if fence is not None:
                return fence, post
            if self._diverged:
                return (b"g" + _BOOTSTRAP_ME.to_bytes(8, "big"),
                        post)
            if seq <= self.last_applied:
                # duplicate ship (our ack was lost): fast-forward the
                # primary — the entry was already applied exactly once
                return (b"k" + self.last_applied.to_bytes(8, "big"),
                        post)
            if seq != self.last_applied + 1:
                return (b"g"
                        + (self.last_applied + 1).to_bytes(8, "big"),
                        post)
            self._apply_entry_locked(entry)
            self.last_applied = seq
            return b"k" + seq.to_bytes(8, "big"), post

    def _heartbeat(self, epoch: int, head: int,
                   base: int) -> tuple[bytes, list]:
        post: list = []
        with self._lock:
            fence = self._gate_epoch_locked(epoch, post, base=base)
            if fence is not None:
                return fence, post
            if self._diverged:
                return (b"g" + _BOOTSTRAP_ME.to_bytes(8, "big"),
                        post)
            if head > self.last_applied:
                return (b"g"
                        + (self.last_applied + 1).to_bytes(8, "big"),
                        post)
            return (b"k" + self.last_applied.to_bytes(8, "big"),
                    post)

    def _bootstrap(self, epoch: int, head: int,
                   data) -> tuple[bytes, list]:
        post: list = []
        snap = transport.unpack_obj(data)
        with self._lock:
            fence = self._gate_epoch_locked(epoch, post)
            if fence is not None:
                return fence, post
            # full-state rewind: replace the inner PS wholesale (no
            # worker server runs on a standby, so nothing aliases it)
            self.ps = _ps_from_snapshot(
                self.rule, snap, snapshot_path=self._snapshot_path,
                snapshot_every=self._snapshot_every)
            self.ps.epoch = int(epoch)
            self.last_applied = int(head)
            self._diverged = False
            return b"k" + int(head).to_bytes(8, "big"), post

    def _apply_entry_locked(self, entry: dict) -> None:
        seq = int(entry["seq"])
        dedupe_seq = None if seq == _NO_SEQ else seq
        if str(entry["kind"]) == "shard_commit":
            self.ps.apply_replicated_shard(
                int(entry["shard"]), int(entry["worker"]),
                bytes(entry["payload"]), dedupe_seq,
                int(entry["staleness"]), bytes(entry["reply"]))
        else:
            self.ps.apply_replicated(
                int(entry["worker"]), bytes(entry["payload"]),
                dedupe_seq, int(entry["staleness"]),
                bytes(entry["reply"]))

    # -- failure detection + election ----------------------------------

    def _monitor_loop(self) -> None:
        # capped: a deposed primary must notice its replicator was
        # fenced promptly even under a lazy election timeout
        poll = min(self.failover_timeout / 4.0, 0.25)
        while not self._stop.wait(poll):
            try:
                self._monitor_tick()
            except Exception:
                continue  # a flaky probe must not kill the monitor

    def _monitor_tick(self) -> None:
        with self._lock:
            role, repl = self.role, self.replicator
        if role == "primary":
            if repl is not None and repl.fenced:
                post: list = []
                with self._lock:
                    if (self.role == "primary"
                            and self.replicator is repl):
                        self._adopt_epoch_locked(
                            int(repl.newer_epoch), post)
                for fn in post:
                    fn()
            return
        with self._lock:
            quiet = telemetry.now() - self._last_contact
            have_peers = len(self.peers) > 0
        if quiet < self.failover_timeout or not have_peers:
            return
        self._run_election()

    def _run_election(self) -> None:
        """The primary went quiet: probe EVERY peer before declaring it
        dead (a slow primary resets the clock), then promote the
        deterministic winner over the reachable candidate set — but
        only with QUORUM: a majority of the group must be accounted
        for, i.e. answered the probe or was confirmed dead by its host
        refusing the connection (``probe_replica``).  An isolated
        standby's probes time out instead of refusing, so it never
        usurps a primary it merely cannot see — and never acks commits
        the healthy majority would later rewind."""
        with self._lock:
            my_epoch = int(self.ps.epoch)
            my_applied = int(self.last_applied)
            peers = list(self.peers)
            index = int(self.index)
        cands = [(my_epoch, my_applied, index)]
        accounted = 1  # self
        primary_alive = False
        for i, peer in enumerate(peers):
            if i == index:
                continue
            st, confirmed_down = probe_replica(
                peer["repl"], timeout=self.probe_timeout)
            if st is None:
                if confirmed_down:
                    accounted += 1
                continue
            accounted += 1
            if st["role"] == "primary" and st["epoch"] >= my_epoch:
                primary_alive = True
            cands.append((st["epoch"], st["last_applied"], i))
        if primary_alive:
            # probe-before-declare-dead: it answered, so the silence
            # was the link or scheduling, not a death — reset the
            # clock instead of deposing a live primary
            with self._lock:
                self._last_contact = telemetry.now()
            return
        if 2 * accounted <= len(peers):
            # no quorum: this node may be the isolated one — stand
            # down and retry after another failover_timeout (the
            # counter makes a stalled, quorum-less group diagnosable)
            telemetry.metrics().counter(
                "ps_election_no_quorum_total").inc()
            with self._lock:
                self._last_contact = telemetry.now()
            return
        if elect(cands) == index:
            self.promote(reason="failover",
                         floor=max(c[0] for c in cands))
        else:
            # the winner gets a full failover_timeout to take over
            # before this node re-opens the election
            with self._lock:
                self._last_contact = telemetry.now()

    # -- snapshot / restart --------------------------------------------

    def snapshot(self) -> dict:
        """The inner PS snapshot (center, clocks, dedupe table, epoch)
        plus this node's replication position — everything a standby
        restart needs to rejoin with a catch-up instead of a full
        bootstrap."""
        with self._lock:
            snap = self.ps.snapshot()
            snap["repl_last_applied"] = int(self.last_applied)
        return snap

    @classmethod
    def from_snapshot(cls, rule: UpdateRule, snapshot: dict,
                      **kwargs) -> "PSReplica":
        """Restart a replica from ``snapshot()`` output: the inner PS
        restores warm (dedupe table included) and ``last_applied``
        resumes from the saved position, so the primary's next append
        finds a standby that only needs the entries it missed while
        down."""
        shards = int(snapshot.get("sharded", 1))
        node = cls(rule, snapshot["center"], num_shards=shards,
                   **kwargs)
        node.ps = _ps_from_snapshot(
            rule, snapshot, snapshot_path=node._snapshot_path,
            snapshot_every=node._snapshot_every)
        node.last_applied = int(snapshot.get("repl_last_applied", 0))
        return node

    # -- shutdown ------------------------------------------------------

    def stop(self) -> None:
        """Graceful teardown (tests' cleanup path — a real failover
        drill uses ``kill``)."""
        self._stop.set()
        with self._lock:
            server, self.server = self.server, None
            repl, self.replicator = self.replicator, None
        if repl is not None:
            repl.stop()
        if server is not None:
            server.stop()
        for s in (self._repl_sock, self._worker_sock,
                  *self._repl_conns):
            try:
                s.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Crash simulation: every socket — worker-facing, replication
        listener, live links — dies at once with no courtesy.  The
        worker server's ``kill`` records the fsynced ``ps_kill``
        flight marker the postmortem keys on."""
        self._stop.set()
        with self._lock:
            server, self.server = self.server, None
            repl, self.replicator = self.replicator, None
        if server is not None:
            server.kill()
        if repl is not None:
            repl.stop()
        for s in (self._repl_sock, self._worker_sock,
                  *self._repl_conns):
            try:
                s.close()
            except OSError:
                pass

    def __enter__(self) -> "PSReplica":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def make_replica_group(rule: UpdateRule, center: Pytree, *,
                       replicas: int = 2, num_shards: int = 1,
                       host: str = "127.0.0.1",
                       **node_kwargs) -> list[PSReplica]:
    """Construct and start an N-replica group in this process: every
    node gets the same ordered peer list (index order = address order =
    election tie-break order) and node 0 is promoted as the initial
    primary (epoch ``N`` — the first mint in node 0's residue class,
    see ``PSReplica.promote``).  Workers connect via
    ``ResilientPSClient.for_replicas([n.worker_address for n in
    nodes], ...)`` — or ``trainers``' ``ps_replicas=`` — and survive a
    ``nodes[0].kill()`` without operator action."""
    if int(replicas) < 1:
        raise ValueError(f"replicas must be >= 1, got {replicas}")
    nodes = [PSReplica(rule, center, num_shards=num_shards,
                       host=host, **node_kwargs)
             for _ in range(int(replicas))]
    specs = [{"worker": n.worker_address, "repl": n.repl_address}
             for n in nodes]
    for i, node in enumerate(nodes):
        node.set_peers(specs, i)
        node.start()
    nodes[0].promote(reason="bootstrap")
    return nodes

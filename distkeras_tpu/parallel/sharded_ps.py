"""Sharded host parameter server — per-shard locks, zero-copy
scatter-gather wire, version-delta pulls (PERF.md §25).

``HostParameterServer`` serializes every ``pull``/``commit`` across all
workers behind ONE mutex and ships the full parameter set both ways on
every exchange, paying ``pack_params``'s double host copy on the path
PERF.md §12 measured as the PS ceiling.  This module shards that hot
loop the way the DistBelief lineage does (Dean et al. partition the
parameter space across server shards; ZeRO partitions optimizer state
the same way):

* the parameter pytree's LEAVES are partitioned into K byte-balanced
  shards (``plan_shards`` — greedy largest-first bin packing, a pure
  function of the template, so both endpoints derive the same plan and
  the wire never carries structure);
* each shard owns its lock, commit clock, per-worker pull clocks,
  bounded staleness window and commit-seq dedupe cache, so commits
  from different workers convoy only when they touch the same shard at
  the same instant — semantically exact for BOTH rule families: every
  rule's ``commit``/``worker_pull`` is per-leaf math (DOWNPOUR/ADAG/
  DynSGD apply additive deltas; the elastic family lerps each leaf
  against the center with the same per-shard staleness a K=1 server
  would compute under a serial schedule, its local tree riding the
  wire as a second frame per shard — the ``b"c"`` convention,
  shard-scoped), and a shard's clock advances exactly like the global
  clock under any full-tree commit schedule;
* the wire speaks shard-addressed ops over the existing framing:
  commits and replies ride ``transport.send_msg_gather`` (one
  ``sendmsg`` over memoryviews of the already-contiguous leaves — no
  ``tobytes`` materialization, no join copy) and are received with
  ``transport.recv_msg_into`` (single-buffer ``recv_into``, leaves
  sliced as zero-copy ``frombuffer`` views);
* pulls are version-delta: the client sends its last-seen per-shard
  clocks and the server ships ONLY shards whose clock advanced — a
  stale-polling or partially-caught-up worker pays bytes proportional
  to what actually changed (``ps_pull_shards_skipped_total`` /
  ``ps_pull_bytes_saved_total``).

Retry semantics are shard-aware for free: ``ResilientPSClient`` stamps
one seq per LOGICAL commit and reuses it across retries, and each
shard dedupes independently — a retry after a mid-commit failure
re-applies exactly the shards that missed and dedupes the ones that
landed (at-most-once per shard, hence per logical commit).

Snapshots are single-file and warm-restart compatible with
``PSServer.restart_from`` (which dispatches on the ``"sharded"`` key);
the periodic form triggers on the LAST shard of a logical commit and
writes under all shard locks before that shard's reply escapes, so an
acked logical commit is durable (``snapshot_every=1`` ⇒ exactly-once
across kill/restart, per-shard dedupe repairing any partially-applied
retry).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional, Sequence

import jax
import numpy as np

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.host_ps import (
    _NO_SEQ,
    _readonly_view,
    _to_numpy,
    HostParameterServer,
    pack_params,
    PSFencedError,
    unpack_params,
)
from distkeras_tpu.parallel.update_rules import PSState, UpdateRule

Pytree = Any

#: wire value for "I have never seen this shard" in versioned pulls
#: (the server ships the shard regardless of its clock)
NEVER_PULLED = 2 ** 64 - 1


def plan_shards(template: Pytree, num_shards: int) -> list[list[int]]:
    """Partition the template's leaves into ``num_shards`` byte-balanced
    groups of flat leaf indices: greedy largest-first onto the lightest
    shard (deterministic — size-desc then index order, ties to the
    lowest shard id), indices re-sorted so every shard preserves
    canonical pytree order.  K is clamped to the leaf count (a shard
    must own at least one leaf); both endpoints compute the identical
    plan from the template they already share, so shard structure
    never crosses the wire."""
    leaves = jax.tree_util.tree_leaves(template)
    if not leaves:
        raise ValueError("cannot shard an empty parameter tree")
    k = max(1, min(int(num_shards), len(leaves)))
    sizes = [int(np.asarray(x).nbytes) for x in leaves]
    order = sorted(range(len(leaves)), key=lambda i: (-sizes[i], i))
    load = [0] * k
    plan: list[list[int]] = [[] for _ in range(k)]
    for i in order:
        j = min(range(k), key=lambda s: (load[s], s))
        plan[j].append(i)
        load[j] += sizes[i]
    for p in plan:
        p.sort()
    return plan


def leaf_nbytes(leaves: Sequence[np.ndarray]) -> int:
    return sum(int(np.asarray(x).nbytes) for x in leaves)


def pack_leaves(leaves, template=None) -> bytes:
    """``host_ps.pack_params`` for a leaf LIST (one shard's slice):
    concatenated contiguous bytes in shard order.  Used only where a
    materialized buffer is required (the dedupe cache, snapshots); the
    wire path gather-sends ``leaf_buffers`` instead."""
    return b"".join(leaf_buffers(leaves, template))


def leaf_buffers(leaves, template=None) -> list[memoryview]:
    """Zero-copy byte views of ``leaves`` for scatter-gather sends
    (copying only leaves that need a dtype cast or are non-contiguous
    — parameter leaves never are in practice)."""
    temps = list(template) if template is not None else None
    out = []
    for i, x in enumerate(leaves):
        arr = np.asarray(x)
        if temps is not None and arr.dtype != temps[i].dtype:
            arr = arr.astype(temps[i].dtype)
        arr = np.ascontiguousarray(arr)
        out.append(memoryview(arr.reshape(-1)).cast("B"))
    return out


def unpack_leaves(template_leaves, data) -> list[np.ndarray]:
    """Zero-copy inverse of the shard wire encoding: read-only
    ``frombuffer`` views sliced per the shard template's leaves."""
    buf = memoryview(data)
    out, off = [], 0
    for t in template_leaves:
        t = np.asarray(t)
        n = int(t.nbytes)
        out.append(np.frombuffer(buf[off:off + n],
                                 dtype=t.dtype).reshape(t.shape))
        off += n
    if off != len(buf):
        raise ValueError(
            f"shard payload is {len(buf)} bytes but the shard "
            f"template expects {off} (mismatched model or shard plan)")
    return out


class _Shard:
    """One shard's whole world: its leaves, lock, clocks and caches."""

    __slots__ = ("idx", "lock", "center", "clock", "pull_clock",
                 "staleness_log", "num_commits", "last_reply",
                 "reply_bytes", "nbytes")

    def __init__(self, idx: list[int], center: list[np.ndarray]):
        self.idx = idx
        self.lock = racecheck.lock("sharded_ps.shard")
        self.center = center
        self.clock = 0
        self.pull_clock: dict[int, int] = {}
        self.staleness_log: list[int] = []
        self.num_commits = 0
        self.last_reply: dict[int, tuple[int, bytes]] = {}
        self.reply_bytes = 0
        self.nbytes = leaf_nbytes(center)


class ShardedParameterServer:
    """Drop-in for ``HostParameterServer`` (same full-tree
    ``pull``/``commit``/liveness/snapshot face, so ``PSServer``,
    ``ResilientPSClient.for_server`` and the trainers compose
    unchanged) plus the per-shard verbs the sharded wire speaks."""

    STALENESS_LOG_WINDOW = HostParameterServer.STALENESS_LOG_WINDOW

    def __init__(self, rule: UpdateRule, center: Pytree,
                 num_shards: int, *,
                 snapshot_path: str | os.PathLike | None = None,
                 snapshot_every: int = 0):
        if int(num_shards) < 1:
            raise ValueError(
                f"num_shards must be >= 1, got {num_shards}")
        self.rule = rule
        leaves, self._treedef = jax.tree_util.tree_flatten(
            _to_numpy(center))
        self._n_leaves = len(leaves)
        self.plan = plan_shards(leaves, num_shards)
        self.num_shards = len(self.plan)
        self._shards = [_Shard(idx, [leaves[i] for i in idx])
                        for idx in self.plan]
        self._seen_lock = racecheck.lock("sharded_ps.seen")
        self._last_seen: dict[int, float] = {}
        # hier_ps leaders: leader id -> (upstream seq, packed center)
        # — group-level dedupe for pre-reduced window commits, kept
        # apart from the per-shard tables (a group commit touches
        # every shard atomically from the leader's point of view)
        self._group_replies: dict[int, tuple[int, bytes]] = {}
        # replication (replicated_ps): same plain attributes as the
        # unsharded server — written at attach/fence, read per commit
        self.epoch = 0
        self._fenced = False
        self._replicator = None
        self.num_snapshots = 0
        self._snapshot_path = snapshot_path
        self._snapshot_every = int(snapshot_every)
        if self._snapshot_every and snapshot_path is None:
            raise ValueError(
                "snapshot_every needs a snapshot_path to write to")

    # -- liveness (one small lock, never nested with shard locks) ----------

    def _stamp(self, worker_id: int) -> None:
        with self._seen_lock:
            self._last_seen[worker_id] = telemetry.now()

    def register(self, worker_id: int) -> None:
        with self._seen_lock:
            self._last_seen.setdefault(worker_id, telemetry.now())
            n = len(self._last_seen)
        telemetry.metrics().gauge("ps_registered_workers").set(n)

    def retire(self, worker_id: int) -> None:
        with self._seen_lock:
            self._last_seen.pop(worker_id, None)
        for shard in self._shards:
            with shard.lock:
                dropped = shard.last_reply.pop(worker_id, None)
                if dropped is not None:
                    shard.reply_bytes -= len(dropped[1])
        self._set_reply_gauge()

    def idle_workers(self, timeout: float) -> list[int]:
        now = telemetry.now()
        with self._seen_lock:
            idle = sorted(w for w, seen in self._last_seen.items()
                          if now - seen > timeout)
            n = len(self._last_seen)
        telemetry.metrics().gauge("ps_idle_workers").set(len(idle))
        telemetry.metrics().gauge("ps_registered_workers").set(n)
        return idle

    def last_acked_seqs(self) -> dict[int, int]:
        """Per-worker last FULLY-acked logical commit seq: the minimum
        across shard dedupe tables (a logical commit is acked only when
        its last shard replied, so a partially-applied commit reports
        the seq its laggard shards hold)."""
        out: dict[int, int] = {}
        for s in self._shards:
            with s.lock:
                for w, (seq, _) in s.last_reply.items():
                    out[int(w)] = min(out.get(int(w), int(seq)),
                                      int(seq))
        return out

    def clear_reply_cache(self) -> None:
        for shard in self._shards:
            with shard.lock:
                shard.last_reply.clear()
                shard.reply_bytes = 0
        with self._seen_lock:
            self._group_replies.clear()
        self._set_reply_gauge()

    def _set_reply_gauge(self) -> None:
        telemetry.metrics().gauge("ps_reply_cache_bytes").set(
            sum(s.reply_bytes for s in self._shards))

    # -- replication (replicated_ps) --------------------------------------

    def attach_replicator(self, replicator) -> None:
        """Install the primary-side log shipper: every shard commit is
        shipped from inside that shard's lock (see ``commit_shard``)."""
        self._replicator = replicator

    def fence(self, epoch: int) -> None:
        """Depose this server (see ``HostParameterServer.fence``)."""
        self._fenced = True
        self.epoch = max(self.epoch, int(epoch))
        telemetry.metrics().counter("ps_fenced_total").inc()

    def apply_replicated_shard(self, shard: int, worker_id: int,
                               payload: bytes, seq: int | None,
                               staleness: int, reply: bytes) -> None:
        """Standby-side replay of one shard commit (the sharded twin
        of ``HostParameterServer.apply_replicated``): the shipped
        staleness and reply bytes are installed verbatim, so center,
        clocks and the per-shard dedupe table all match the primary."""
        s = self._shards[shard]
        with s.lock:
            leaves = unpack_leaves(s.center, payload)
            state = PSState(center=s.center, clock=np.int32(s.clock))
            new_state = self.rule.commit(state, leaves,
                                         np.int32(staleness))
            s.center = [np.asarray(x) for x in new_state.center]
            s.clock += 1
            s.pull_clock[worker_id] = s.clock
            s.staleness_log.append(int(staleness))
            if len(s.staleness_log) > \
                    self.STALENESS_LOG_WINDOW * 5 // 4:
                del s.staleness_log[:-self.STALENESS_LOG_WINDOW]
            s.num_commits += 1
            if seq is not None:
                old = s.last_reply.get(worker_id)
                if old is not None:
                    s.reply_bytes -= len(old[1])
                s.last_reply[worker_id] = (int(seq), bytes(reply))
                s.reply_bytes += len(reply)
            if (shard == self.num_shards - 1 and self._snapshot_every
                    and s.num_commits % self._snapshot_every == 0):
                self._write_snapshot_holding(shard)

    # -- per-shard verbs (the sharded wire) --------------------------------

    def shard_clocks(self) -> list[int]:
        return [s.clock for s in self._shards]

    def pull_shard(self, worker_id: int, shard: int
                   ) -> tuple[int, list[np.ndarray]]:
        """One shard's ``(clock, read-only leaves)``; stamps the
        worker's pull clock for that shard's staleness bookkeeping."""
        s = self._shards[shard]
        with s.lock:
            s.pull_clock[worker_id] = s.clock
            return s.clock, [_readonly_view(x) for x in s.center]

    def pull_since(self, worker_id: int, since: Sequence[int]
                   ) -> tuple[list[tuple[int, int, list[np.ndarray]]],
                              int, int]:
        """Version-delta pull: ``(included, skipped_shards,
        skipped_bytes)`` where ``included`` lists ``(shard, clock,
        read-only leaves)`` for every shard whose clock advanced past
        ``since[shard]`` (``NEVER_PULLED`` forces inclusion).  Every
        shard — shipped or skipped — stamps the worker's pull clock:
        a skipped shard's center is, by definition of the skip, exactly
        what the worker already holds."""
        if len(since) != self.num_shards:
            raise ValueError(
                f"versioned pull carries {len(since)} clocks, server "
                f"has {self.num_shards} shards (mismatched plan)")
        m = telemetry.metrics()
        m.counter("ps_pulls_total").inc()
        included, skipped, saved = [], 0, 0
        for k, s in enumerate(self._shards):
            with s.lock:
                s.pull_clock[worker_id] = s.clock
                if since[k] != NEVER_PULLED and s.clock <= since[k]:
                    skipped += 1
                    saved += s.nbytes
                    continue
                included.append(
                    (k, s.clock, [_readonly_view(x)
                                  for x in s.center]))
        self._stamp(worker_id)
        if skipped:
            m.counter("ps_pull_shards_skipped_total").inc(skipped)
            m.counter("ps_pull_bytes_saved_total").inc(saved)
        return included, skipped, saved

    def commit_shard(self, worker_id: int, shard: int,
                     leaves: Sequence[np.ndarray],
                     local: Optional[Sequence[np.ndarray]] = None,
                     seq: int | None = None
                     ) -> tuple[int, list[np.ndarray]]:
        """Apply one shard's slice of a logical commit under THAT
        shard's lock only; returns ``(shard clock after, read-only
        pulled leaves)``.  ``seq`` dedupes per shard — a retried
        logical commit re-applies exactly the shards that missed."""
        s = self._shards[shard]
        m = telemetry.metrics()
        leaves = [np.asarray(x) for x in leaves]
        if local is not None:
            local = [np.asarray(x) for x in local]
        wait0 = telemetry.now()
        waiters = m.gauge("ps_commit_waiters")
        waiters.inc()
        s.lock.acquire()
        waiters.dec()
        m.counter("ps_lock_wait_seconds_total").inc(
            telemetry.now() - wait0)
        try:
            with telemetry.span("ps_shard_commit", worker=worker_id,
                                shard=shard):
                if self._fenced:
                    raise PSFencedError(
                        f"commit rejected: this server was deposed "
                        f"(a newer primary holds epoch > "
                        f"{self.epoch})")
                if seq is not None:
                    last = s.last_reply.get(worker_id)
                    if last is not None and seq <= last[0]:
                        self._stamp(worker_id)
                        m.counter("ps_commit_dedup_total").inc()
                        return s.clock, unpack_leaves(s.center,
                                                      last[1])
                staleness = s.clock - s.pull_clock.get(worker_id, 0)
                state = PSState(center=s.center,
                                clock=np.int32(s.clock))
                new_state = self.rule.commit(state, leaves,
                                             np.int32(staleness))
                pulled = self.rule.worker_pull(local, state.center,
                                               new_state.center)
                s.center = [np.asarray(x) for x in new_state.center]
                s.clock += 1
                s.pull_clock[worker_id] = s.clock
                s.staleness_log.append(int(staleness))
                if len(s.staleness_log) > \
                        self.STALENESS_LOG_WINDOW * 5 // 4:
                    del s.staleness_log[:-self.STALENESS_LOG_WINDOW]
                s.num_commits += 1
                m.counter("ps_shard_commits_total").inc()
                m.histogram("ps_commit_staleness",
                            buckets=telemetry.STALENESS_BUCKETS
                            ).observe(int(staleness))
                pulled = [np.asarray(x) for x in pulled]
                reply_packed = b""
                if seq is not None:
                    old = s.last_reply.get(worker_id)
                    if old is not None:
                        s.reply_bytes -= len(old[1])
                    reply_packed = pack_leaves(pulled)
                    s.last_reply[worker_id] = (seq, reply_packed)
                    s.reply_bytes += len(reply_packed)
                if self._replicator is not None:
                    # under THIS shard's lock, before the reply
                    # escapes: the log's per-shard subsequence matches
                    # the shard-lock order, so the standby's replay
                    # reconstructs each shard byte-identically
                    self._replicator.replicate(
                        kind="shard_commit", worker=worker_id,
                        shard=shard,
                        payload=pack_leaves(leaves, s.center),
                        seq=_NO_SEQ if seq is None else int(seq),
                        staleness=int(staleness),
                        reply=reply_packed)
                if shard == self.num_shards - 1:
                    m.counter("ps_commits_total").inc()
                    # one flight event per LOGICAL commit (its last
                    # shard), not one per shard — the recorder stays
                    # proportional to commits
                    # lint: allow(blocking-call-under-lock): acked =>
                    # durable — recorded under the last shard's lock so
                    # no later commit can be acked first
                    flight_recorder.record(
                        "commit", worker=worker_id, seq=seq,
                        clock=s.clock, shards=self.num_shards,
                        staleness=int(staleness))
                    if (self._snapshot_every and s.num_commits
                            % self._snapshot_every == 0):
                        # the logical commit's other shards applied
                        # before this one (shard order is the client
                        # contract); snapshot under ALL locks before
                        # this last reply escapes: acked ⇒ durable
                        self._write_snapshot_holding(shard)
                self._stamp(worker_id)
                return s.clock, [_readonly_view(x) for x in pulled]
        finally:
            s.lock.release()
            if seq is not None:
                self._set_reply_gauge()

    def commit_group(self, leader_id: int, fold: Pytree,
                     staleness, workers,
                     seq: int | None = None) -> Pytree:
        """Sharded twin of ``HostParameterServer.commit_group``: the
        pre-reduced window's leaves are added shard by shard (each
        under its own lock, in shard order — the same discipline as a
        full-tree commit), with dedupe at GROUP level keyed by the
        leader's upstream seq.  Each shard's clock advances by the
        constituent count and the staleness vector lands in every
        shard's log (a group commit touches every shard, exactly like
        a logical commit).  Returns the new full center."""
        if self.rule.payload_kind != "delta":
            raise ValueError(
                f"hierarchical aggregation needs a delta-family "
                f"rule; {type(self.rule).__name__} commits "
                f"{self.rule.payload_kind!r} payloads")
        if self._fenced:
            raise PSFencedError(
                f"commit rejected: this server was deposed (a newer "
                f"primary holds epoch > {self.epoch})")
        if self._replicator is not None:
            raise RuntimeError(
                "hierarchical upstream commits do not compose with "
                "primary/standby replication (the standby replay "
                "re-runs the rule's single-commit law, not the "
                "group fold)")
        fold_leaves = jax.tree_util.tree_leaves(_to_numpy(fold))
        if len(fold_leaves) != self._n_leaves:
            raise ValueError(
                f"fold has {len(fold_leaves)} leaves, server "
                f"template has {self._n_leaves}")
        n = len(workers)
        staleness = [int(s) for s in staleness]
        m = telemetry.metrics()
        with telemetry.span("ps_commit", worker=leader_id, fanin=n):
            if seq is not None:
                with self._seen_lock:
                    last = self._group_replies.get(leader_id)
                if last is not None and seq <= last[0]:
                    self._stamp(leader_id)
                    m.counter("ps_commit_dedup_total").inc()
                    flight_recorder.record("commit_dedup",
                                           worker=leader_id, seq=seq)
                    return unpack_params(self.center, last[1])
            hist = m.histogram("ps_commit_staleness",
                               buckets=telemetry.STALENESS_BUCKETS)
            for k, s in enumerate(self._shards):
                with s.lock:
                    s.center = [np.asarray(c + fold_leaves[i])
                                for c, i in zip(s.center, s.idx)]
                    s.clock += n
                    s.pull_clock[leader_id] = s.clock
                    s.staleness_log.extend(staleness)
                    if len(s.staleness_log) > \
                            self.STALENESS_LOG_WINDOW * 5 // 4:
                        del s.staleness_log[:-self
                                            .STALENESS_LOG_WINDOW]
                    before = s.num_commits
                    s.num_commits += n
                    m.counter("ps_shard_commits_total").inc(n)
                    if k == self.num_shards - 1:
                        m.counter("ps_commits_total").inc(n)
                        m.counter("ps_upstream_commits_total").inc()
                        m.gauge("ps_fanin_reduction").set(n)
                        for st in staleness:
                            hist.observe(st)
                        # lint: allow(blocking-call-under-lock):
                        # acked => durable, same contract as
                        # commit_shard's last-shard record
                        flight_recorder.record(
                            "commit", worker=leader_id, seq=seq,
                            clock=s.clock, shards=self.num_shards,
                            fanin=n,
                            staleness=max(staleness, default=0))
                        if (self._snapshot_every
                                and s.num_commits
                                // self._snapshot_every
                                > before // self._snapshot_every):
                            self._write_snapshot_holding(k)
            center = self.center
            if seq is not None:
                with self._seen_lock:
                    self._group_replies[leader_id] = (
                        int(seq), pack_params(center))
            self._stamp(leader_id)
            return center

    # -- the full-tree face (in-process arm, PSClient compat) --------------

    def pull(self, worker_id: int) -> Pytree:
        telemetry.metrics().counter("ps_pulls_total").inc()
        out: list = [None] * self._n_leaves
        for s in self._shards:
            with s.lock:
                s.pull_clock[worker_id] = s.clock
                for i, x in zip(s.idx, s.center):
                    out[i] = _readonly_view(x)
        self._stamp(worker_id)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def commit(self, worker_id: int, payload: Pytree,
               local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        """Full-tree commit as K shard commits in shard order (the
        same order the sharded wire client uses, which is what makes
        the last shard the snapshot trigger); shard locks are taken
        one at a time — never nested — so commits from different
        workers interleave per shard instead of convoying."""
        leaves = jax.tree_util.tree_leaves(_to_numpy(payload))
        if len(leaves) != self._n_leaves:
            raise ValueError(
                f"payload has {len(leaves)} leaves, server template "
                f"has {self._n_leaves}")
        local_leaves = (None if local is None
                        else jax.tree_util.tree_leaves(
                            _to_numpy(local)))
        out: list = [None] * self._n_leaves
        for k, s in enumerate(self._shards):
            _, pulled = self.commit_shard(
                worker_id, k, [leaves[i] for i in s.idx],
                None if local_leaves is None
                else [local_leaves[i] for i in s.idx], seq=seq)
            for i, x in zip(s.idx, pulled):
                out[i] = x
        return jax.tree_util.tree_unflatten(self._treedef, out)

    @property
    def center(self) -> Pytree:
        out: list = [None] * self._n_leaves
        for s in self._shards:
            with s.lock:
                for i, x in zip(s.idx, s.center):
                    out[i] = _readonly_view(x)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    @property
    def staleness_log(self) -> list[int]:
        """Shard 0's (bounded) staleness window — the representative
        sequence: every logical commit touches every shard, so under
        any serial schedule shard 0's log equals the unsharded
        server's.  Per-shard distributions live in the
        ``ps_commit_staleness`` telemetry histogram."""
        return self._shards[0].staleness_log

    @property
    def num_commits(self) -> int:
        """Logical commits (every one touches shard 0)."""
        return self._shards[0].num_commits

    # -- snapshot / warm restart ------------------------------------------

    def _snapshot_holding(self, held: int | None) -> dict:
        """Build the snapshot dict, acquiring every shard lock not
        already ``held`` (in index order — the only multi-lock path in
        the class, so ordering is trivially safe)."""
        taken = []
        try:
            for k, s in enumerate(self._shards):
                if k != held:
                    s.lock.acquire()
                    taken.append(s)
            return self._build_snapshot_all_locked()
        finally:
            for s in taken:
                s.lock.release()

    def replication_snapshot(self, head_fn) -> tuple[int, dict]:
        """A ``(replication-log head seq, snapshot dict)`` pair that is
        CONSISTENT: both are read under ALL shard locks, where no
        shard commit — hence no log-seq assignment (``commit_shard``
        replicates inside its shard's lock) — can be mid-flight, so
        the snapshot contains exactly the commits through ``head``
        (the standby bootstrap's correctness condition; ``head_fn`` is
        the replicator's ``head_seq``, and lock order stays shard ->
        replicator, same as the in-commit ship path)."""
        taken = []
        try:
            for s in self._shards:
                s.lock.acquire()
                taken.append(s)
            return int(head_fn()), self._build_snapshot_all_locked()
        finally:
            for s in taken:
                s.lock.release()

    def _build_snapshot_all_locked(self) -> dict:
        center: list = [None] * self._n_leaves
        shards = []
        for s in self._shards:
            for i, x in zip(s.idx, s.center):
                center[i] = x
            shards.append({
                "clock": s.clock,
                "num_commits": s.num_commits,
                "pull_clock": {str(w): c
                               for w, c in s.pull_clock.items()},
                "staleness_log": np.asarray(s.staleness_log,
                                            np.int64),
                "last_reply": {str(w): {"seq": np.uint64(seq),
                                        "packed": packed}
                               for w, (seq, packed)
                               in s.last_reply.items()},
            })
        return {
            "sharded": self.num_shards,
            "epoch": self.epoch,
            "center": jax.tree_util.tree_unflatten(self._treedef,
                                                   center),
            "shards": shards,
        }

    def snapshot(self) -> dict:
        """Point-in-time warm-restart state across ALL shards (taken
        under every shard lock): full center plus per-shard clocks,
        pull clocks, staleness windows and dedupe caches."""
        return self._snapshot_holding(None)

    def _write_snapshot_holding(self, held: int) -> None:
        from distkeras_tpu import checkpoint as ckpt

        with telemetry.span("ps_snapshot",
                            commits=self._shards[held].num_commits):
            snap = self._snapshot_holding(held)
            ckpt.save_ps_snapshot(self._snapshot_path, snap)
        self.num_snapshots += 1
        telemetry.metrics().counter("ps_snapshots_total").inc()
        # fully-acked seq per worker = min across the shard dedupe
        # tables just captured (same law as ``last_acked_seqs``)
        acked: dict[str, int] = {}
        for saved in snap["shards"]:
            for w, e in saved["last_reply"].items():
                seq = int(e["seq"])
                acked[w] = min(acked.get(w, seq), seq)
        flight_recorder.record(
            "snapshot", path=os.fspath(self._snapshot_path),
            num_commits=int(self._shards[0].num_commits),
            last_acked=acked)

    def save_snapshot(self, path: str | os.PathLike) -> str:
        from distkeras_tpu import checkpoint as ckpt

        return ckpt.save_ps_snapshot(path, self.snapshot())

    @classmethod
    def from_snapshot(cls, rule: UpdateRule,
                      snapshot: dict | str | os.PathLike, *,
                      snapshot_path: str | os.PathLike | None = None,
                      snapshot_every: int = 0
                      ) -> "ShardedParameterServer":
        """Warm restart; the shard plan is recomputed from the saved
        center (same deterministic function of the template), so the
        snapshot carries no structure beyond the shard count."""
        if isinstance(snapshot, (str, os.PathLike)):
            from distkeras_tpu import checkpoint as ckpt

            snapshot = ckpt.load_ps_snapshot(snapshot)
        if "sharded" not in snapshot:
            raise ValueError(
                "not a sharded PS snapshot; restore with "
                "HostParameterServer.from_snapshot")
        ps = cls(rule, snapshot["center"],
                 int(snapshot["sharded"]),
                 snapshot_path=snapshot_path,
                 snapshot_every=snapshot_every)
        ps.epoch = int(snapshot.get("epoch", 0))
        if len(snapshot["shards"]) != ps.num_shards:
            raise ValueError(
                f"snapshot holds {len(snapshot['shards'])} shards, "
                f"plan derived {ps.num_shards}")
        for s, saved in zip(ps._shards, snapshot["shards"]):
            s.clock = int(saved["clock"])
            s.num_commits = int(saved["num_commits"])
            s.pull_clock = {int(w): int(c) for w, c
                            in saved["pull_clock"].items()}
            s.staleness_log = [int(x) for x
                               in np.asarray(saved["staleness_log"])]
            s.last_reply = {int(w): (int(e["seq"]),
                                     bytes(e["packed"]))
                            for w, e in saved["last_reply"].items()}
            s.reply_bytes = sum(len(p) for _, p
                                in s.last_reply.values())
        ps._set_reply_gauge()
        return ps


class ShardedPSClient:
    """Worker-side connection speaking the shard-addressed wire ops
    against a ``PSServer`` fronting a ``ShardedParameterServer``.

    Same face as ``PSClient`` (``pull``/``commit``/``done``/``close``)
    so ``ResilientPSClient`` wraps it unchanged; a reconnect rebuilds
    the client with empty version caches (the first pull after a
    failure is a full pull — correct, just unsaved bytes).

    ``commit`` splits the payload by the shared shard plan and walks
    the shards in order, one request/reply per shard, each applied
    under only that shard's server-side lock; the SAME logical seq
    rides every shard, so a retried commit is deduped or applied
    independently per shard (at-most-once per shard).  ``pull`` is
    version-delta: unchanged shards are served from the client's own
    cache and never touch the wire.
    """

    def __init__(self, host: str, port: int, worker_id: int,
                 template: Pytree, num_shards: int, codec=None,
                 stats: Optional[dict] = None):
        """``num_shards`` is the deployment contract: client and server
        derive the identical plan from (template, K) — a mismatched K
        surfaces as a clock-count/shard-id error on the first op.
        ``stats`` (optional dict) accumulates ``pull_shards_skipped``
        / ``pull_bytes_saved`` across ops — shared by the trainer's
        worker threads to feed history."""
        from distkeras_tpu.parallel.compression import resolve_codec

        self.worker_id = int(worker_id)
        self._template_leaves, self._treedef = \
            jax.tree_util.tree_flatten(_to_numpy(template))
        self._bind_plan(int(num_shards))
        self.codec = resolve_codec(codec)
        self._stats = stats if stats is not None else {}
        self._stats.setdefault("pull_shards_skipped", 0)
        self._stats.setdefault("pull_bytes_saved", 0)
        self._sock = transport.connect(host, port, timeout=30.0)
        hello = int(worker_id).to_bytes(4, "big")
        if self.codec is not None:
            server_side = resolve_codec(self.codec.name)
            if type(server_side) is not type(self.codec):
                raise ValueError(
                    f"codec {type(self.codec).__name__} cannot be "
                    "reconstructed server-side from its name")
            hello += self.codec.name.encode()
        transport.send_msg(self._sock, hello)

    def _bind_plan(self, num_shards: int) -> None:
        self.plan = plan_shards(self._template_leaves, num_shards)
        self.num_shards = len(self.plan)
        self._shard_templates = [[self._template_leaves[i]
                                  for i in idx] for idx in self.plan]
        self._clocks = [NEVER_PULLED] * self.num_shards
        self._have: list[Optional[list[np.ndarray]]] = \
            [None] * self.num_shards

    def _assemble(self) -> Pytree:
        out: list = [None] * len(self._template_leaves)
        for idx, leaves in zip(self.plan, self._have):
            for i, x in zip(idx, leaves):
                out[i] = x
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def pull(self) -> Pytree:
        body = b"".join(int(c).to_bytes(8, "big")
                        for c in self._clocks)
        with telemetry.span("ps_client_pull",
                            worker=self.worker_id) as sp:
            hdr = transport.trace_header()
            transport.send_msg(self._sock, hdr + b"P", body)
            if hdr:
                telemetry.flow_start("wire", sp.span_id, op="pull")
            reply = transport.recv_msg_into(self._sock)
        count = int.from_bytes(reply[:2], "big")
        off = 2 + 10 * count
        fresh = set()
        for e in range(count):
            head = reply[2 + 10 * e: 2 + 10 * e + 10]
            k = int.from_bytes(head[:2], "big")
            clock = int.from_bytes(head[2:], "big")
            temps = self._shard_templates[k]
            n = leaf_nbytes(temps)
            self._have[k] = unpack_leaves(temps, reply[off:off + n])
            self._clocks[k] = clock
            fresh.add(k)
            off += n
        skipped = saved = 0
        for k in range(self.num_shards):
            if k in fresh:
                continue
            if self._have[k] is None:
                raise ConnectionError(
                    f"server skipped shard {k} this client never "
                    "pulled (mismatched shard plan?)")
            skipped += 1
            saved += leaf_nbytes(self._shard_templates[k])
        self._stats["pull_shards_skipped"] += skipped
        self._stats["pull_bytes_saved"] += saved
        return self._assemble()

    def commit(self, payload, local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        wire_seq = _NO_SEQ if seq is None else int(seq)
        if seq is not None and not 0 <= wire_seq < _NO_SEQ:
            raise ValueError(f"seq out of range [0, 2**64-1): {seq}")
        if isinstance(payload, (list, tuple)):  # pre-encoded per shard
            if self.codec is None:
                raise ValueError(
                    "pre-encoded shard bytes need a codec declared at "
                    "connect time")
            if len(payload) != self.num_shards:
                raise ValueError(
                    f"{len(payload)} encoded shards for "
                    f"{self.num_shards}-shard plan")
            bodies = list(payload)
        else:
            leaves = jax.tree_util.tree_leaves(_to_numpy(payload))
            shards = [[leaves[i] for i in idx] for idx in self.plan]
            if self.codec is not None:
                bodies = [self.codec.encode_leaves(s) for s in shards]
            else:
                bodies = shards
        local_shards = None
        if local is not None:
            # elastic family (pull_uses_local): the local slice for
            # each shard rides as a second frame after the commit
            # frame — the shard-scoped twin of the b"c" convention
            if isinstance(payload, (list, tuple)):
                raise ValueError(
                    "pre-encoded shard bytes cannot carry a local "
                    "tree (the elastic family does not compress)")
            local_leaves = jax.tree_util.tree_leaves(_to_numpy(local))
            local_shards = [[local_leaves[i] for i in idx]
                            for idx in self.plan]
        with telemetry.span("ps_client_commit",
                            worker=self.worker_id, seq=seq):
            for k, body in enumerate(bodies):
                head = (b"C" + int(k).to_bytes(2, "big")
                        + wire_seq.to_bytes(8, "big"))
                # per-shard sub-span: each shard request is its own
                # wire round trip, so each gets its own flow arrow
                with telemetry.span("ps_client_shard_commit",
                                    shard=k) as sp:
                    hdr = transport.trace_header()
                    if isinstance(body, (bytes, bytearray)):
                        transport.send_msg_gather(
                            self._sock, hdr + head, body)
                    else:
                        transport.send_msg_gather(
                            self._sock, hdr + head,
                            *leaf_buffers(body,
                                          self._shard_templates[k]))
                    if local_shards is not None:
                        transport.send_msg_gather(
                            self._sock,
                            *leaf_buffers(local_shards[k],
                                          self._shard_templates[k]))
                    if hdr:
                        telemetry.flow_start(
                            "wire", sp.span_id, op="shard_commit",
                            shard=k, seq=seq)
                    reply = transport.recv_msg_into(self._sock)
                self._clocks[k] = int.from_bytes(reply[:8], "big")
                self._have[k] = unpack_leaves(
                    self._shard_templates[k], reply[8:])
        return self._assemble()

    def done(self):
        transport.send_msg(self._sock, b"d")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass

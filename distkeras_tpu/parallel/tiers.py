"""Lowering tiers: the fidelity registry (ISSUE 12 satellite).

``fidelity=`` used to be validated by ad-hoc string checks scattered
across ``trainers.py`` (``fidelity != "host"``, ``not in ("faithful",
...)``); every new arm meant hunting them all down.  This table is the
one place a tier's capabilities live — trainers resolve the string
once and gate each feature on a capability flag, so adding a tier
touches one row and every error message can list the valid choices.

The tiers are *lowerings* of the same PS-round semantics:

* ``host`` — the host-wire control+data plane: free-running worker
  threads racing against a concurrent host parameter server (real TCP
  optional).  Nondeterministic by design; the arm chaos/replication/
  snapshot suites run on.
* ``faithful`` / ``fast`` — the on-mesh *emulated* rounds
  (``ps_emulator``): one XLA program per round, commits serialized by
  a seeded permutation (faithful scans them; fast collapses the
  linear rules to a closed form).
* ``mesh`` — the on-chip compiled data plane (``ps_dataplane``): one
  SPMD shard_map program per round with the center *sharded* over the
  ``workers`` axis, delta reduction lowered to reduce-scatter, and
  donated state buffers.  Implements the ``fast`` tier's closed-form
  center trajectory (same seeded ``commit_permutation``), plus a
  pipelined variant matching ``make_pipelined_round_fn``'s +W offset.

``analysis/surfaces.py`` cross-checks the ``TIERS`` keys against the
docs/API.md "Lowering tiers" table, so a tier added here without docs
fails ``lint_static.py``.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class LoweringTier:
    """Capabilities of one ``fidelity=`` lowering tier."""

    name: str
    #: "host-wire" (threads + transport), "emulated" (one XLA program
    #: per round, stacked workers), or "mesh" (one SPMD shard_map
    #: program per round, sharded center)
    data_plane: str
    #: real concurrency (racing threads): gates the host-only kwargs
    #: (transport/fault injection/compression/external PS/shards/...)
    concurrent: bool
    #: bit-replayable under a fixed seed
    deterministic: bool
    #: supports commit_overlap=True (a commit phase that can pipeline
    #: against the next window)
    commit_overlap: bool
    #: supports model_parallel > 1 (tensor-parallel worker programs)
    model_parallel: bool
    #: supports checkpoint/resume of mid-training state
    checkpoint: bool
    #: lowers comm compression INSIDE the compiled round
    #: (``comm_dtype``/``comm_codec``/``metrics_every`` kwargs); the
    #: host arm's ``compression=`` wire codecs are a separate,
    #: host-side feature gated on ``concurrent``
    comm_compression: bool
    #: supports sampled round attribution (the ``attrib_every`` kwarg:
    #: ``MeshRoundDriver`` step-time decomposition + the XLA cost
    #: ledger's mfu_observed/mfu_roofline pair) — requires the
    #: AOT-compiled round programs only the mesh data plane has
    round_attrib: bool


TIERS = {
    "host": LoweringTier(
        name="host", data_plane="host-wire", concurrent=True,
        deterministic=False, commit_overlap=True, model_parallel=False,
        checkpoint=False, comm_compression=False, round_attrib=False),
    "faithful": LoweringTier(
        name="faithful", data_plane="emulated", concurrent=False,
        deterministic=True, commit_overlap=True, model_parallel=True,
        checkpoint=True, comm_compression=False, round_attrib=False),
    "fast": LoweringTier(
        name="fast", data_plane="emulated", concurrent=False,
        deterministic=True, commit_overlap=False, model_parallel=True,
        checkpoint=True, comm_compression=False, round_attrib=False),
    "mesh": LoweringTier(
        name="mesh", data_plane="mesh", concurrent=False,
        deterministic=True, commit_overlap=True, model_parallel=False,
        checkpoint=False, comm_compression=True, round_attrib=True),
}


def valid_tiers() -> list[str]:
    return sorted(TIERS)


def tiers_with(capability: str) -> list[str]:
    """Tier names whose ``capability`` flag is set — for error messages
    that must tell the user which fidelities DO support a feature."""
    return sorted(n for n, t in TIERS.items()
                  if getattr(t, capability))


def resolve_tier(name: str) -> LoweringTier:
    if name not in TIERS:
        raise ValueError(
            f"unknown fidelity {name!r}; valid lowering tiers: "
            f"{valid_tiers()}")
    return TIERS[name]

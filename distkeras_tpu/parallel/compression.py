"""Commit-payload compression for the PS wire (L1).

The reference shipped every window's full weight delta as an
uncompressed pickle over TCP (SURVEY.md §3.2 hot-loop observation (b) —
"communication payload is the full weight set, uncompressed, per
window").  This module is the TPU-rebuild's answer for the DCN arm: a
delta codec quantizes (``int8``), sparsifies (``topk``), or narrows
(``bfloat16``) the commit payload before it hits the socket, and the
worker loop keeps the quantization *residual* locally, folding it into
the next window's delta (error feedback) so the lossy wire still
converges to the same optimum.

Codecs apply to the **delta family** of update rules (DOWNPOUR / ADAG /
DynSGD — ``payload_kind == 'delta'``): a delta is an additive update,
so an under-transmitted remainder can ride the next commit.  The
elastic family commits absolute parameters; lossy compression there
would not be error-correctable, and the trainer rejects it.

Wire format: msgpack list of per-leaf dicts (raw little-endian array
bytes + the codec's side data), ordered by the pytree flattening of the
parameter template both ends already share — no pickle, matching the
``parallel.transport`` policy.
"""

from __future__ import annotations

from typing import Any

import jax
import msgpack
import numpy as np

Pytree = Any


class DeltaCodec:
    """Base codec: per-leaf encode/decode over the template's
    flattening order."""

    name: str = "identity"

    def encode_leaf(self, x: np.ndarray) -> dict:
        raise NotImplementedError

    def decode_leaf(self, enc: dict, shape, dtype) -> np.ndarray:
        raise NotImplementedError

    def encode_leaves(self, leaves) -> bytes:
        """Encode an ordered leaf LIST — the per-shard unit the
        sharded PS wire commits (``parallel.sharded_ps``); the
        full-tree ``encode`` is the K=1 special case."""
        return msgpack.packb(
            [self.encode_leaf(np.asarray(x, np.float32))
             for x in leaves])

    def decode_leaves(self, data, template_leaves) -> list:
        """Inverse of ``encode_leaves`` against the shard's template
        leaves (shapes/dtypes)."""
        enc = msgpack.unpackb(data)
        if len(enc) != len(template_leaves):
            raise ValueError(
                f"encoded payload has {len(enc)} leaves, template has "
                f"{len(template_leaves)}")
        return [self.decode_leaf(e, np.shape(t), np.asarray(t).dtype)
                for e, t in zip(enc, template_leaves)]

    def encode(self, tree: Pytree) -> bytes:
        return self.encode_leaves(jax.tree_util.tree_leaves(tree))

    def decode(self, data: bytes, template: Pytree) -> Pytree:
        leaves, treedef = jax.tree_util.tree_flatten(template)
        return jax.tree_util.tree_unflatten(
            treedef, self.decode_leaves(data, leaves))

    def round_trip(self, tree: Pytree) -> tuple[bytes, Pytree]:
        """``(wire bytes, the tree the receiver will reconstruct)`` —
        the reconstruction is what error feedback subtracts."""
        data = self.encode(tree)
        return data, self.decode(data, tree)

    def round_trip_shards(self, tree: Pytree, plan
                          ) -> tuple[list[bytes], Pytree]:
        """Per-shard ``round_trip``: encode each shard's leaf slice
        separately (``plan`` is ``sharded_ps.plan_shards`` output) so
        the worker loop encodes ONCE and hands the ready shard bodies
        to ``ShardedPSClient.commit``; the decoded reassembly is what
        error feedback subtracts — identical math to the full-tree
        ``round_trip`` (the codec is per-leaf)."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        datas = [self.encode_leaves([leaves[i] for i in idx])
                 for idx in plan]
        out = [None] * len(leaves)
        for idx, data in zip(plan, datas):
            for i, leaf in zip(idx, self.decode_leaves(
                    data, [leaves[i] for i in idx])):
                out[i] = leaf
        return datas, jax.tree_util.tree_unflatten(treedef, out)


class Int8Codec(DeltaCodec):
    """Per-leaf symmetric int8 quantization: ``scale = max|x| / 127``,
    ~4x smaller than f32 on the wire."""

    name = "int8"

    def encode_leaf(self, x):
        amax = float(np.max(np.abs(x))) if x.size else 0.0
        scale = amax / 127.0 if amax > 0 else 1.0
        q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
        return {"s": scale, "q": q.tobytes()}

    def decode_leaf(self, enc, shape, dtype):
        q = np.frombuffer(enc["q"], np.int8).reshape(shape)
        return (q.astype(np.float32) * np.float32(enc["s"])).astype(
            dtype)


class TopKCodec(DeltaCodec):
    """Per-leaf magnitude top-k sparsification: transmit the largest
    ``fraction`` of entries (at least one) as (uint32 index, f32 value)
    pairs — ~``8 * fraction`` bytes per original 4-byte entry."""

    name = "topk"

    def __init__(self, fraction: float = 0.01):
        if not 0.0 < fraction <= 1.0:
            raise ValueError(
                f"fraction must be in (0, 1], got {fraction}")
        self.fraction = float(fraction)
        self.name = f"topk:{self.fraction}"

    def encode_leaf(self, x):
        flat = x.ravel()
        k = max(1, int(round(self.fraction * flat.size)))
        if k >= flat.size:
            idx = np.arange(flat.size, dtype=np.uint32)
        else:
            idx = np.argpartition(np.abs(flat),
                                  -k)[-k:].astype(np.uint32)
        return {"i": idx.tobytes(),
                "v": flat[idx].astype(np.float32).tobytes()}

    def decode_leaf(self, enc, shape, dtype):
        idx = np.frombuffer(enc["i"], np.uint32)
        vals = np.frombuffer(enc["v"], np.float32)
        out = np.zeros(int(np.prod(shape, dtype=np.int64)), np.float32)
        out[idx] = vals
        return out.reshape(shape).astype(dtype)


class Bf16Codec(DeltaCodec):
    """Cast values to bfloat16 on the wire — 2x smaller, mild loss,
    residual-corrected like the rest."""

    name = "bfloat16"

    def encode_leaf(self, x):
        import ml_dtypes

        return {"b": x.astype(ml_dtypes.bfloat16).tobytes()}

    def decode_leaf(self, enc, shape, dtype):
        import ml_dtypes

        b = np.frombuffer(enc["b"], ml_dtypes.bfloat16).reshape(shape)
        return b.astype(np.float32).astype(dtype)


def resolve_codec(spec) -> DeltaCodec | None:
    """``None`` | codec instance | name: ``'int8'``, ``'bfloat16'``
    (``'bf16'``), ``'topk'`` or ``'topk:<fraction>'``."""
    if spec is None or isinstance(spec, DeltaCodec):
        return spec
    if isinstance(spec, str):
        if spec == "int8":
            return Int8Codec()
        if spec in ("bf16", "bfloat16"):
            return Bf16Codec()
        if spec == "topk":
            return TopKCodec()
        if spec.startswith("topk:"):
            return TopKCodec(float(spec.split(":", 1)[1]))
        raise KeyError(
            f"unknown compression {spec!r}; known: 'int8', "
            f"'bfloat16', 'topk', 'topk:<fraction>'")
    raise TypeError(f"cannot resolve a codec from {type(spec)}")


def raw_nbytes(tree: Pytree) -> int:
    """Uncompressed wire size of a pytree (f32 leaf bytes) — the
    baseline the compression telemetry is measured against."""
    return sum(4 * int(np.size(x))
               for x in jax.tree_util.tree_leaves(tree))

"""Expert parallelism: a top-k gated MoE layer over a mesh axis.

Beyond the reference (SURVEY.md §2.3: "Expert parallelism: NO") —
the last of the five parallelism forms (dp/tp/sp/pp/ep).  Experts'
FFN parameters are sharded over the ``expert`` mesh axis; tokens are
routed with the einsum dispatch/combine formulation (Shazeer et al.'s
Mesh-TF layout — ``top_k=1`` is the Switch layer, ``top_k=2`` the
GShard-style router) and exchanged with ``lax.all_to_all`` over ICI:

1. router: per-token logits over all E experts, top-k gates;
2. dispatch einsum builds ``[E, C, d]`` capacity-bucketed inputs;
3. ``all_to_all`` turns token-sharding into expert-sharding — each
   device receives ITS experts' buckets from every device;
4. the local experts' FFNs run (vmapped);
5. a reverse ``all_to_all`` + combine einsum returns gated outputs to
   the tokens' home devices.

Tokens over a full expert's capacity ``C = ceil(T_local/E *
capacity_factor)`` are dropped (standard Switch behavior; the gate
residual keeps training stable) and reported via the aux outputs,
along with the load-balancing auxiliary loss from the Switch paper.

SPMD: call inside ``jax.shard_map`` with tokens sharded over
``axis_name`` and ``params`` sharded on their leading (expert) axis.
Differentiable end to end (autodiff reverses the all_to_alls).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from distkeras_tpu.utils import axis_size


class MoEParams(NamedTuple):
    """``router``: [d, E] (replicated).  ``w_in``: [E_local, d, h],
    ``b_in``: [E_local, h], ``w_out``: [E_local, h, d], ``b_out``:
    [E_local, d] — leading axis sharded over the expert mesh axis."""

    router: jax.Array
    w_in: jax.Array
    b_in: jax.Array
    w_out: jax.Array
    b_out: jax.Array


def init_moe_params(rng: jax.Array, d_model: int, d_hidden: int,
                    num_experts: int) -> MoEParams:
    """Global (unsharded) parameters; shard leading expert axes over
    the mesh axis when placing them."""
    k1, k2, k3 = jax.random.split(rng, 3)
    s_in = 1.0 / math.sqrt(d_model)
    s_out = 1.0 / math.sqrt(d_hidden)
    return MoEParams(
        router=jax.random.normal(k1, (d_model, num_experts)) * s_in,
        w_in=jax.random.normal(
            k2, (num_experts, d_model, d_hidden)) * s_in,
        b_in=jnp.zeros((num_experts, d_hidden)),
        w_out=jax.random.normal(
            k3, (num_experts, d_hidden, d_model)) * s_out,
        b_out=jnp.zeros((num_experts, d_model)),
    )


def moe_pspecs(axis: str = "expert") -> "MoEParams":
    """The ``shard_map`` in_specs for ``MoEParams``: router replicated,
    every expert stack sharded on its leading axis.  One definition so
    call sites can't drift from the field order."""
    from jax.sharding import PartitionSpec as P

    return MoEParams(P(), P(axis), P(axis), P(axis), P(axis))


class MoEAux(NamedTuple):
    load_balance_loss: jax.Array  # scalar; add (scaled) to the loss
    dropped_fraction: jax.Array   # scalar in [0, 1]


def expert_capacity(num_tokens: int, num_experts: int,
                    capacity_factor: float, top_k: int = 1) -> int:
    """Per-expert bucket size: ``ceil(T * k * factor / E)``, min 1 —
    the one capacity policy shared by ``moe_apply`` and the model-zoo
    ``MoEFFN``."""
    return max(1, math.ceil(
        num_tokens * top_k * capacity_factor / num_experts))


def routing(x, router, num_experts, capacity, top_k=1):
    """Top-k dispatch/combine tensors ([T, E, C]) + aux telemetry.

    ``top_k=1`` is the Switch layer; ``top_k=2`` the GShard-style
    routing (gates renormalized over the chosen experts; later choices
    fill capacity after earlier ones, so a token's second expert is
    dropped before its first).

    All bookkeeping runs in f32 regardless of ``x.dtype``: bf16 cumsum
    loses integer exactness past 256, which would assign two tokens the
    same capacity slot and silently merge their embeddings.  Only the
    final dispatch/combine tensors are cast back."""
    t = x.shape[0]
    logits = (x.astype(jnp.float32)
              @ router.astype(jnp.float32))      # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, top_k)       # [T, k]
    # Switch (k=1) gates with the raw probability; GShard (k>1)
    # renormalizes over the chosen experts.
    gates = (top_p if top_k == 1
             else top_p / top_p.sum(axis=-1, keepdims=True))
    dispatch = jnp.zeros((t, num_experts, capacity), jnp.float32)
    combine = jnp.zeros((t, num_experts, capacity), jnp.float32)
    counts = jnp.zeros((num_experts,), jnp.float32)  # filled slots
    kept = jnp.float32(0.0)
    mask1 = None  # the j=0 mask, reused for the aux loss
    for j in range(top_k):  # static, tiny k
        mask = jax.nn.one_hot(top_i[:, j], num_experts,
                              dtype=jnp.float32)  # [T, E]
        if j == 0:
            mask1 = mask
        # position within the expert's bucket, offset by the slots
        # already filled by earlier choices
        pos = ((jnp.cumsum(mask, axis=0) - 1.0)
               + counts[None, :]) * mask
        keep = (pos < capacity).astype(jnp.float32) * mask
        d_j = keep[..., None] * jax.nn.one_hot(
            pos.astype(jnp.int32), capacity,
            dtype=jnp.float32)                   # [T, E, C]
        dispatch = dispatch + d_j
        combine = combine + d_j * gates[:, j][:, None, None]
        counts = counts + keep.sum(axis=0)  # kept only: slots stay dense
        kept = kept + keep.sum()
    # Switch aux loss on the primary choice:
    # E * sum_e( frac_tokens_e * mean_prob_e )
    lb = num_experts * jnp.sum(mask1.mean(axis=0) * probs.mean(axis=0))
    dropped = jnp.clip(1.0 - kept / (t * top_k), 0.0, 1.0)
    return (dispatch.astype(x.dtype), combine.astype(x.dtype),
            MoEAux(lb, dropped))


def moe_apply(params: MoEParams, x: jax.Array, *, axis_name: str,
              capacity_factor: float = 1.25, top_k: int = 1
              ) -> tuple[jax.Array, MoEAux]:
    """Apply the expert-parallel MoE FFN to ``x`` ``[T_local, d]``.

    ``params`` leaves other than ``router`` carry this device's
    ``E_local = E / n_devices`` experts.  ``top_k=1`` is Switch
    routing; ``top_k=2`` GShard-style (renormalized gates over the
    chosen experts).  Returns ``([T_local, d], MoEAux)``; aux values
    are means over the mesh axis.
    """
    n_dev = axis_size(axis_name)
    e_local = params.w_in.shape[0]
    num_experts = e_local * n_dev
    if not 1 <= top_k <= num_experts:
        raise ValueError(
            f"top_k={top_k} out of range [1, {num_experts}]")
    t_local, d = x.shape
    capacity = expert_capacity(t_local, num_experts, capacity_factor,
                               top_k)

    dispatch, combine, aux = routing(x, params.router, num_experts,
                                     capacity, top_k)

    # [T, E, C] -> expert-major input buckets [E, C, d]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    # Token-sharded -> expert-sharded: split the (global) expert axis
    # across devices, concatenate the senders' buckets on a new axis.
    # [E, C, d] -> [n_dev(senders), E_local, C, d]
    expert_in = expert_in.reshape(n_dev, e_local, capacity, d)
    expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                               concat_axis=0, tiled=False)
    # merge sender x capacity: [E_local, n_dev * C, d]
    expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
        e_local, n_dev * capacity, d)

    def ffn(w_in, b_in, w_out, b_out, h):
        return jax.nn.relu(h @ w_in + b_in) @ w_out + b_out

    expert_out = jax.vmap(ffn)(params.w_in, params.b_in, params.w_out,
                               params.b_out, expert_in)

    # Back to token-sharding: inverse reshape + all_to_all.
    expert_out = expert_out.reshape(
        e_local, n_dev, capacity, d).transpose(1, 0, 2, 3)
    expert_out = lax.all_to_all(expert_out, axis_name, split_axis=0,
                                concat_axis=0, tiled=False)
    expert_out = expert_out.reshape(num_experts, capacity, d)
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, MoEAux(
        lax.pmean(aux.load_balance_loss, axis_name),
        lax.pmean(aux.dropped_fraction, axis_name))

"""On-chip compiled PS data plane — the ``fidelity="mesh"`` tier.

The emulated rounds (``ps_emulator``) are one XLA program per round,
but their data plane still *looks* like a parameter server: the center
is replicated, every round materializes a ``[W, params]`` pulled stack
(``_broadcast_like``), and the closed-form commit is a ``tensordot``
against a replicated center.  This module lowers the same round to the
layout the SNIPPETS exemplars (pjit + donated buffers + partition
rules) and the original port brief ("gradient push/pull lowered to ICI
all-reduce / async reduce-scatter") actually describe:

* the center lives *sharded*: packed per-dtype into 1-D buffers and
  split row-wise ``[W, block]`` over the ``workers`` mesh axis — each
  device owns exactly one shard (a ZeRO-style layout for the PS);
* one ``shard_map`` program runs the whole round: the round-start pull
  is an ``all_gather`` of the center shards fused into the program (no
  W-way host-visible replication), each device runs its worker's
  window locally, and the scaled deltas are folded into the center by
  a single ``psum_scatter`` (reduce-scatter) — each device updates its
  own shard and never sees the others';
* PS state and worker states are donated (``donate_argnums``), so the
  round updates HBM in place instead of double-buffering ``[W,
  params]`` trees;
* worker params are not carried between rounds at all: for the
  delta family the round-barrier pull makes them a pure function of
  the center, so ``MeshWorkerState`` is ``TrainState`` minus
  ``params``.

Partition specs for the worker state (optimizer moments, batch stats,
rng streams) come from a small regex-rule → PartitionSpec-pytree
resolver (``match_partition_rules``, the SNIPPETS [2] shape) layered
on ``mesh.py``'s NamedShardings.

Semantics are the ``fast`` tier's closed form, exactly: the center
trajectory for DOWNPOUR/ADAG/DynSGD matches ``ps_emulator._fast_round``
under the same seeded ``commit_permutation`` (DynSGD's per-commit
``1/(position+1)`` scale is applied per device before the reduce).
The pipelined variant matches ``make_pipelined_round_fn``'s contract:
window *k* overlaps the commit of round *k-1*'s pending payloads at
staleness ``position + W``, and ``flush`` drains the final pending at
its true depth (offset 0).  The elastic family commits absolute
params against a serialized center — structurally not a reduction —
and stays on the faithful/host tiers.

Compile-guard telemetry: each distinct round shape traces exactly one
program, counted by ``ps_round_compiles_total{fidelity="mesh"}``
(``"mesh_pipelined"`` for the pipelined variant) — the same
trace-time counter contract as the emulated tiers.
"""

from __future__ import annotations

import math
import re
from typing import Any, Mapping, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import PartitionSpec as P

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu import telemetry, utils
from distkeras_tpu.parallel.update_rules import (
    DynSGDRule,
    PSState,
    UpdateRule,
)
from distkeras_tpu.workers import TrainState, make_window_runner

Pytree = Any


# ---------------------------------------------------------------------------
# Regex partition rules -> PartitionSpec pytree (SNIPPETS [2] shape).
# ---------------------------------------------------------------------------

#: default rules for the stacked ``[W, ...]`` worker state: every
#: non-scalar leaf shards its leading (worker) axis over the mesh's
#: ``workers`` axis.  Override per-dataplane to co-shard large moments
#: differently (future model-parallel tiers).
DEFAULT_WORKER_RULES = ((r".*", P(mesh_lib.WORKER_AXIS)),)


def _path_str(path) -> str:
    """KeyPath -> ``a/b/0/c`` string the rule regexes match against."""
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(k.name)
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.FlattenedIndexKey):
            parts.append(str(k.key))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return "/".join(parts)


def match_partition_rules(rules, tree: Pytree) -> Pytree:
    """``((regex, PartitionSpec), ...)`` -> PartitionSpec pytree.

    First rule whose pattern ``re.search``-matches the leaf's
    '/'-joined key path wins.  Scalar (size <= 1) leaves always get
    ``P()`` — there is nothing to shard and replicating them keeps
    every rule set valid for optimizer step counters.  A leaf no rule
    matches raises, naming the path — silent replication is how layout
    bugs hide.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def assign(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if math.prod(shape) <= 1:
            return P()
        name = _path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                return spec
        raise ValueError(
            f"no partition rule matches leaf {name!r} "
            f"(shape {shape}); add a rule (regex, PartitionSpec) "
            f"covering it")

    return jax.tree_util.tree_map_with_path(assign, tree)


# ---------------------------------------------------------------------------
# Packed center layout: per-dtype 1-D buffers, padded to W, sharded
# row-wise [W, block] over the workers axis.
# ---------------------------------------------------------------------------


class _Group(NamedTuple):
    indices: tuple[int, ...]   # leaf indices (flatten order)
    offsets: dict[int, int]    # leaf index -> offset into the buffer
    total: int                 # payload elements (before padding)
    padded: int                # total rounded up to a multiple of W


class _FlatSpec:
    """Host-side description of the center's packed layout.

    Pure shape metadata: ``pack``/``pack_flat``/``unpack`` are
    static-shape jittable tree <-> buffer transforms.
    """

    def __init__(self, template: Pytree, num_shards: int):
        leaves, treedef = jax.tree_util.tree_flatten(template)
        if not leaves:
            raise ValueError("empty parameter tree")
        self.treedef = treedef
        self.shapes = [tuple(x.shape) for x in leaves]
        self.dtypes = [jnp.dtype(x.dtype) for x in leaves]
        self.sizes = [int(math.prod(s)) for s in self.shapes]
        self.num_shards = int(num_shards)
        by_dtype: dict[str, list[int]] = {}
        for i, dt in enumerate(self.dtypes):
            by_dtype.setdefault(dt.name, []).append(i)
        self.groups: dict[str, _Group] = {}
        for name, idxs in sorted(by_dtype.items()):
            offsets, off = {}, 0
            for i in idxs:
                offsets[i] = off
                off += self.sizes[i]
            padded = -(-max(off, 1) // num_shards) * num_shards
            self.groups[name] = _Group(tuple(idxs), offsets, off, padded)

    def pack_flat(self, tree: Pytree) -> dict[str, jnp.ndarray]:
        """Tree -> ``{dtype: [padded]}`` full-length 1-D buffers."""
        leaves = self.treedef.flatten_up_to(tree)
        out = {}
        for name, g in self.groups.items():
            flat = jnp.concatenate(
                [jnp.ravel(leaves[i]) for i in g.indices])
            if g.padded > g.total:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((g.padded - g.total,), flat.dtype)])
            out[name] = flat
        return out

    def pack(self, tree: Pytree) -> dict[str, jnp.ndarray]:
        """Tree -> ``{dtype: [W, block]}`` row-sharded center blocks."""
        return {
            name: flat.reshape(self.num_shards, -1)
            for name, flat in self.pack_flat(tree).items()}

    def unpack(self, flats: Mapping[str, jnp.ndarray]) -> Pytree:
        """``{dtype: [padded]}`` -> tree (inverse of ``pack_flat``)."""
        leaves: list = [None] * len(self.shapes)
        for name, g in self.groups.items():
            flat = flats[name]
            for i in g.indices:
                off = g.offsets[i]
                leaves[i] = flat[off:off + self.sizes[i]].reshape(
                    self.shapes[i])
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


# ---------------------------------------------------------------------------
# States.
# ---------------------------------------------------------------------------


class MeshPSState(struct.PyTreeNode):
    """Sharded-center PS state.

    ``blocks`` maps dtype name -> ``[W, block]`` packed center rows
    (row *w* lives on worker *w*'s device); ``clock`` is the replicated
    commit clock (same meaning as ``PSState.clock``).
    """

    blocks: Mapping[str, jnp.ndarray]
    clock: jnp.ndarray


class MeshWorkerState(struct.PyTreeNode):
    """``TrainState`` minus ``params``, stacked ``[W, ...]``.

    Between mesh rounds the delta family's worker params are a pure
    function of the center (round-barrier pull), so carrying them
    would re-create exactly the ``[W, params]`` replication this tier
    deletes.
    """

    step: jnp.ndarray
    opt_state: Pytree
    model_state: Mapping[str, Pytree]
    rng: jax.Array


# ---------------------------------------------------------------------------
# The dataplane.
# ---------------------------------------------------------------------------


class MeshDataplane:
    """One compiled SPMD program per PS round (see module docstring).

    ``round``/``flush`` mirror the emulated signatures so the trainer
    loop drives either tier unchanged:

    * plain:     ``round(ps, ws, batch, perm) -> (ps, ws, metrics)``
    * pipelined: ``round(ps, ws, batch, perm, pending, pending_perm,
      pending_valid) -> (ps, ws, metrics, pending, perm, valid)`` and
      ``flush(ps, pending, pending_perm) -> ps``

    with ``ps``/``ws`` in this module's sharded layout — convert a
    host-layout ``(PSState, TrainState)`` pair with ``to_device`` once
    before the first round, and read results back via ``center`` /
    ``export_ps_state``.
    """

    def __init__(self, rule: UpdateRule, step_fn, mesh,
                 center_template: Pytree, *, pipelined: bool = False,
                 partition_rules=DEFAULT_WORKER_RULES):
        if rule.payload_kind != "delta":
            raise ValueError(
                "fidelity='mesh' compiles the delta-family commit "
                "(DOWNPOUR/ADAG/DynSGD) into a reduce-scatter; the "
                "elastic family commits absolute params against a "
                "serialized center — use fidelity='faithful' or "
                "'host'")
        if mesh_lib.WORKER_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh has no {mesh_lib.WORKER_AXIS!r} axis: "
                f"{mesh.axis_names}")
        extra = [a for a in mesh.axis_names
                 if a != mesh_lib.WORKER_AXIS and mesh.shape[a] > 1]
        if extra:
            raise ValueError(
                "fidelity='mesh' is data-parallel only (one worker "
                f"per device); mesh has extra axes {extra}")
        self.rule = rule
        self.mesh = mesh
        self.num_workers = int(mesh.shape[mesh_lib.WORKER_AXIS])
        self.pipelined = bool(pipelined)
        self.partition_rules = tuple(partition_rules)
        self._window_run = make_window_runner(step_fn)
        self.spec = _FlatSpec(center_template, self.num_workers)
        self._rep = mesh_lib.replicated_sharding(mesh)
        self._row = mesh_lib.batch_sharding(mesh)
        self._block_shardings = {n: self._row for n in self.spec.groups}
        self._pack_jit = jax.jit(self.spec.pack,
                                 out_shardings=self._block_shardings)
        self._center_jit = jax.jit(
            lambda mps: self.spec.unpack(
                {n: b.reshape(-1) for n, b in mps.blocks.items()}),
            out_shardings=self._rep)
        self._ws_specs = None  # resolved on first to_device

    # -- state conversion ------------------------------------------------

    def to_device(self, ps_state: PSState, worker_states: TrainState
                  ) -> tuple[MeshPSState, MeshWorkerState]:
        """Host/emulated layout -> this tier's sharded layout.

        Must be called once before ``round`` (it also resolves the
        worker partition specs from the concrete state shapes and
        finalizes the compiled programs).
        """
        mws = MeshWorkerState(
            step=worker_states.step, opt_state=worker_states.opt_state,
            model_state=worker_states.model_state,
            rng=worker_states.rng)
        if self._ws_specs is None:
            self._build_programs(mws)
        mws = jax.device_put(mws, self._ws_shardings)
        blocks = self._pack_jit(ps_state.center)
        clock = jax.device_put(jnp.asarray(ps_state.clock), self._rep)
        return MeshPSState(blocks=blocks, clock=clock), mws

    def center(self, mps: MeshPSState) -> Pytree:
        """Replicated center pytree (for eval/export); one compiled
        gather+unpack program, shared by every call."""
        return self._center_jit(mps)

    def export_ps_state(self, mps: MeshPSState) -> PSState:
        """Sharded layout -> the emulated tiers' ``PSState``."""
        return PSState(center=self.center(mps), clock=mps.clock)

    def init_pending(self) -> dict[str, jnp.ndarray]:
        """Zero pending payloads ``{dtype: [W, padded]}`` (inert for
        the delta family until the first round marks them valid)."""
        out = {}
        for name, g in self.spec.groups.items():
            dt = jnp.dtype(name)
            out[name] = jax.device_put(
                jnp.zeros((self.num_workers, g.padded), dt), self._row)
        return out

    # -- program construction --------------------------------------------

    def _build_programs(self, template: MeshWorkerState) -> None:
        specs = match_partition_rules(self.partition_rules, template)
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        paths = jax.tree_util.tree_flatten_with_path(template)[0]
        spec_leaves = jax.tree_util.tree_leaves(specs, is_leaf=is_spec)
        for (path, leaf), sp in zip(paths, spec_leaves):
            if math.prod(tuple(leaf.shape)) <= 1:
                continue
            if not len(sp) or sp[0] != mesh_lib.WORKER_AXIS:
                raise ValueError(
                    "mesh-tier worker leaves are stacked [W, ...] and "
                    "must shard the leading axis over "
                    f"{mesh_lib.WORKER_AXIS!r}; rule resolved "
                    f"{_path_str(path)!r} to {sp}")
        self._ws_specs = specs
        self._ws_shardings = mesh_lib.shardings_for(self.mesh, specs)

        spec = self.spec
        rule = self.rule
        W = self.num_workers
        WA = mesh_lib.WORKER_AXIS
        dyn = isinstance(rule, DynSGDRule)
        window_run = self._window_run
        row_blocks = {n: P(WA) for n in spec.groups}

        def _local(tree):
            return jax.tree_util.tree_map(lambda x: x[0], tree)

        def _stacked(tree):
            return jax.tree_util.tree_map(lambda x: x[None], tree)

        def window_and_delta(blocks, ws, batch):
            # Fused round-start pull: ONE all-gather of the center
            # shards per device — the program's only full-center copy.
            center_flat = {
                n: jax.lax.all_gather(b[0], WA, tiled=True)
                for n, b in blocks.items()}
            center = spec.unpack(center_flat)
            state = TrainState(
                step=ws.step[0], params=center,
                opt_state=_local(ws.opt_state),
                model_state=_local(ws.model_state), rng=ws.rng[0])
            local_batch = _local(batch)
            window = jax.tree_util.tree_leaves(
                local_batch)[0].shape[0]
            new_state, step_metrics = window_run(state, local_batch)
            delta = rule.normalize_delta(
                utils.tree_sub(new_state.params, center), window)
            new_ws = MeshWorkerState(
                step=new_state.step[None],
                opt_state=_stacked(new_state.opt_state),
                model_state=_stacked(new_state.model_state),
                rng=new_state.rng[None])
            return spec.pack_flat(delta), new_ws, step_metrics

        def commit(blocks, flat, scale):
            # Per-device scaled payload -> reduce-scatter -> each
            # device folds the reduction into its own center shard.
            out = {}
            for n, b in blocks.items():
                scaled = flat[n] * scale.astype(flat[n].dtype)
                out[n] = b + jax.lax.psum_scatter(
                    scaled, WA, tiled=True)[None]
            return out

        def round_body(blocks, clock, ws, batch, inv):
            flat, new_ws, sm = window_and_delta(blocks, ws, batch)
            pos = inv[jax.lax.axis_index(WA)]
            scale = (1.0 / (pos.astype(jnp.float32) + 1.0) if dyn
                     else jnp.float32(1.0))
            new_blocks = commit(blocks, flat, scale)
            metrics = {
                "loss": sm["loss"].mean()[None],
                "grad_norm": sm["grad_norm"].mean()[None],
                "staleness": pos.astype(jnp.int32)[None],
            }
            return new_blocks, clock + W, new_ws, metrics

        round_smap = utils.shard_map(
            round_body, mesh=self.mesh,
            in_specs=(row_blocks, P(), specs, P(WA), P()),
            out_specs=(row_blocks, P(), specs, P(WA)))

        def plain_round(mps, mws, batch, perm):
            # Python side effect at TRACE time only — the public
            # one-compile-per-round-shape guard (same contract as the
            # emulated tiers' counter).
            telemetry.metrics().counter(
                "ps_round_compiles_total", fidelity="mesh").inc()
            inv = jnp.argsort(perm)
            blocks, clock, ws, metrics = round_smap(
                mps.blocks, mps.clock, mws, batch, inv)
            return (MeshPSState(blocks=blocks, clock=clock), ws,
                    metrics)

        def pipe_body(blocks, clock, ws, batch, inv, pending, pinv,
                      pvalid):
            # window k (on the pre-commit center) and the commit of
            # round k-1's pending are independent subgraphs — XLA
            # overlaps them, same contract as make_pipelined_round_fn.
            flat, new_ws, sm = window_and_delta(blocks, ws, batch)
            pos = inv[jax.lax.axis_index(WA)]
            ppos = pinv[jax.lax.axis_index(WA)]
            pscale = (1.0 / (ppos.astype(jnp.float32) + W + 1.0)
                      if dyn else jnp.float32(1.0))
            pscale = pscale * pvalid.astype(jnp.float32)
            new_blocks = commit(
                blocks, {n: p[0] for n, p in pending.items()}, pscale)
            new_clock = clock + W * pvalid.astype(clock.dtype)
            metrics = {
                "loss": sm["loss"].mean()[None],
                "grad_norm": sm["grad_norm"].mean()[None],
                # true commit depth: one full round behind + position
                "staleness": (pos + W).astype(jnp.int32)[None],
            }
            new_pending = {n: f[None] for n, f in flat.items()}
            return (new_blocks, new_clock, new_ws, metrics,
                    new_pending, jnp.asarray(True))

        pipe_smap = utils.shard_map(
            pipe_body, mesh=self.mesh,
            in_specs=(row_blocks, P(), specs, P(WA), P(),
                      {n: P(WA) for n in spec.groups}, P(), P()),
            out_specs=(row_blocks, P(), specs, P(WA),
                       {n: P(WA) for n in spec.groups}, P()))

        def pipe_round(mps, mws, batch, perm, pending, pending_perm,
                       pending_valid):
            telemetry.metrics().counter(
                "ps_round_compiles_total",
                fidelity="mesh_pipelined").inc()
            inv = jnp.argsort(perm)
            pinv = jnp.argsort(pending_perm)
            (blocks, clock, ws, metrics, new_pending,
             valid) = pipe_smap(mps.blocks, mps.clock, mws, batch,
                                inv, pending, pinv, pending_valid)
            return (MeshPSState(blocks=blocks, clock=clock), ws,
                    metrics, new_pending, perm, valid)

        def flush_body(blocks, clock, pending, pinv):
            # drain at TRUE depth: no window ran ahead -> offset 0
            ppos = pinv[jax.lax.axis_index(WA)]
            scale = (1.0 / (ppos.astype(jnp.float32) + 1.0) if dyn
                     else jnp.float32(1.0))
            new_blocks = commit(
                blocks, {n: p[0] for n, p in pending.items()}, scale)
            return new_blocks, clock + W

        flush_smap = utils.shard_map(
            flush_body, mesh=self.mesh,
            in_specs=(row_blocks, P(),
                      {n: P(WA) for n in spec.groups}, P()),
            out_specs=(row_blocks, P()))

        def flush_fn(mps, pending, pending_perm):
            pinv = jnp.argsort(pending_perm)
            blocks, clock = flush_smap(mps.blocks, mps.clock, pending,
                                       pinv)
            return MeshPSState(blocks=blocks, clock=clock)

        if self.pipelined:
            self.round = jax.jit(pipe_round, donate_argnums=(0, 1, 4))
            self.flush = jax.jit(flush_fn, donate_argnums=(0, 1))
        else:
            self.round = jax.jit(plain_round, donate_argnums=(0, 1))

"""On-chip compiled PS data plane — the ``fidelity="mesh"`` tier.

The emulated rounds (``ps_emulator``) are one XLA program per round,
but their data plane still *looks* like a parameter server: the center
is replicated, every round materializes a ``[W, params]`` pulled stack
(``_broadcast_like``), and the closed-form commit is a ``tensordot``
against a replicated center.  This module lowers the same round to the
layout the SNIPPETS exemplars (pjit + donated buffers + partition
rules) and the original port brief ("gradient push/pull lowered to ICI
all-reduce / async reduce-scatter") actually describe:

* the center lives *sharded*: packed per-dtype into 1-D buffers and
  split row-wise ``[W, block]`` over the ``workers`` mesh axis — each
  device owns exactly one shard (a ZeRO-style layout for the PS);
* one ``shard_map`` program runs the whole round: the round-start pull
  is an ``all_gather`` of the center shards fused into the program (no
  W-way host-visible replication), each device runs its worker's
  window locally, and the scaled deltas are folded into the center by
  a single ``psum_scatter`` (reduce-scatter) — each device updates its
  own shard and never sees the others';
* PS state and worker states are donated (``donate_argnums``), so the
  round updates HBM in place instead of double-buffering ``[W,
  params]`` trees;
* worker params are not carried between rounds at all: for the
  delta family the round-barrier pull makes them a pure function of
  the center, so ``MeshWorkerState`` is ``TrainState`` minus
  ``params``.

Communication compression (ISSUE 16 tentpole) — two independent knobs,
both lowered INSIDE the compiled round, mirroring the host wire codecs
(``parallel.compression``) which remain the parity oracle:

* ``comm_codec="int8"`` replaces the f32 center ``all_gather`` with an
  int8 one: each device quantizes its own shard with PER-LEAF symmetric
  scales computed on-device (partial per-leaf ``segment_max`` over the
  local block, ``pmax`` across shards — the exact global ``max|x|``,
  then ``scale = amax/127``, ``clip(round(x/scale))`` — the same law as
  ``compression.Int8Codec``, float32 scale math instead of the host
  codec's float64).  Dequantization is FUSED into the per-leaf unpack
  (each leaf is sliced from the int8 buffer and multiplied by its
  scalar scale), so no f32 intermediate of the full packed center ever
  materializes — the program's only full-center transfer is 1 byte per
  element plus one [n_leaves] scale vector.  The center shards
  themselves stay exact f32; only the broadcast is lossy, and the
  commit folds each worker's delta (computed against the center it
  actually saw) into the exact shards.
* ``comm_dtype="bfloat16"`` narrows the delta reduce-scatter: the
  scaled f32 payload is cast to bf16 (the ``Bf16Codec`` law:
  round-to-nearest-even) before ``psum_scatter`` and the reduction is
  widened back into the f32 shard.  Unlike the host codec the
  reduction itself runs in bf16 (the wire IS the reduction here), so
  end-to-end tolerance is documented looser than the cast law.

Both knobs apply to the float32 groups only; other dtypes ride
uncompressed.  ``comm_bytes_per_round`` / ``comm_bytes_saved_per_round``
expose the static per-round wire accounting (remote fraction of each
collective, all devices), and every dispatched round increments
``ps_round_comm_bytes_saved_total`` by the saving.

Async host dispatch (tentpole 3): per-round metrics (loss / grad_norm /
staleness, each ``[W]``) no longer return as a per-round dict — they
accumulate into a device-resident ring of ``metrics_every`` rounds
(``init_ring()``), written at a traced slot index so the slot never
retraces.  ``MeshRoundDriver`` owns the dispatch loop: it enqueues
round k+1 before fetching round k's metrics, fetches a completed ring
only after at least one newer round is in flight
(``ps_metrics_fetches_total`` counts the device reads), and its
``sync=True`` mode is the eager-fetch oracle the async path is tested
byte-identical against.

Semantics are the ``fast`` tier's closed form, exactly: the center
trajectory for DOWNPOUR/ADAG/DynSGD matches ``ps_emulator._fast_round``
under the same seeded ``commit_permutation`` (DynSGD's per-commit
``1/(position+1)`` scale is applied per device before the reduce).
The pipelined variant matches ``make_pipelined_round_fn``'s contract:
window *k* overlaps the commit of round *k-1*'s pending payloads at
staleness ``position + W``, and ``flush`` drains the final pending at
its true depth (offset 0).  The elastic family commits absolute
params against a serialized center — structurally not a reduction —
and stays on the faithful/host tiers.

Compile-guard telemetry: each distinct (round shape x comm config)
traces exactly one program, counted by
``ps_round_compiles_total{fidelity="mesh"}`` (``"mesh_pipelined"`` for
the pipelined variant) — the same trace-time counter contract as the
emulated tiers.
"""

from __future__ import annotations

import collections
import math
import re
import time
from typing import Any, Mapping, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import PartitionSpec as P

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu import telemetry, utils
from distkeras_tpu.parallel.update_rules import (
    DynSGDRule,
    PSState,
    UpdateRule,
)
from distkeras_tpu.workers import TrainState, make_window_runner

Pytree = Any

#: valid ``comm_dtype`` values (the delta reduce-scatter element type)
COMM_DTYPES = ("float32", "bfloat16")
#: valid ``comm_codec`` values (the center re-broadcast codec)
COMM_CODECS = (None, "int8")


# ---------------------------------------------------------------------------
# On-chip codec law — jnp mirror of ``compression.Int8Codec`` /
# ``Bf16Codec`` (the host parity oracles).
# ---------------------------------------------------------------------------


def quantize_int8(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization, the ``Int8Codec`` law on-device:
    ``scale = max|x|/127`` (1.0 when all-zero), ``q = clip(round(
    x/scale), -127, 127)``.  Scale math is float32 (the host codec
    computes it in float64 — parity to rtol ~1e-6, documented in
    ``tests/test_ps_dataplane.py``)."""
    x = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(x)) if x.size else jnp.float32(0.0)
    scale = jnp.where(amax > 0, amax / jnp.float32(127.0),
                      jnp.float32(1.0))
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jnp.ndarray, scale) -> jnp.ndarray:
    """Inverse of ``quantize_int8`` (== ``Int8Codec.decode_leaf``)."""
    return q.astype(jnp.float32) * jnp.float32(scale)


# ---------------------------------------------------------------------------
# Regex partition rules -> PartitionSpec pytree (SNIPPETS [2] shape).
# ---------------------------------------------------------------------------

#: default rules for the stacked ``[W, ...]`` worker state: every
#: non-scalar leaf shards its leading (worker) axis over the mesh's
#: ``workers`` axis.  Override per-dataplane to co-shard large moments
#: differently (future model-parallel tiers).
DEFAULT_WORKER_RULES = ((r".*", P(mesh_lib.WORKER_AXIS)),)


def _path_str(path) -> str:
    """KeyPath -> ``a/b/0/c`` string the rule regexes match against."""
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(k.name)
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.FlattenedIndexKey):
            parts.append(str(k.key))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return "/".join(parts)


def match_partition_rules(rules, tree: Pytree) -> Pytree:
    """``((regex, PartitionSpec), ...)`` -> PartitionSpec pytree.

    First rule whose pattern ``re.search``-matches the leaf's
    '/'-joined key path wins.  Scalar (size <= 1) leaves always get
    ``P()`` — there is nothing to shard and replicating them keeps
    every rule set valid for optimizer step counters.  A leaf no rule
    matches raises, naming the path — silent replication is how layout
    bugs hide.
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def assign(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if math.prod(shape) <= 1:
            return P()
        name = _path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                return spec
        raise ValueError(
            f"no partition rule matches leaf {name!r} "
            f"(shape {shape}); add a rule (regex, PartitionSpec) "
            f"covering it")

    return jax.tree_util.tree_map_with_path(assign, tree)


# ---------------------------------------------------------------------------
# Packed center layout: per-dtype 1-D buffers, padded to W, sharded
# row-wise [W, block] over the workers axis.
# ---------------------------------------------------------------------------


class _Group(NamedTuple):
    indices: tuple[int, ...]   # leaf indices (flatten order)
    offsets: dict[int, int]    # leaf index -> offset into the buffer
    total: int                 # payload elements (before padding)
    padded: int                # total rounded up to a multiple of W


class _FlatSpec:
    """Host-side description of the center's packed layout.

    Pure shape metadata: ``pack``/``pack_flat``/``unpack`` are
    static-shape jittable tree <-> buffer transforms.
    """

    def __init__(self, template: Pytree, num_shards: int):
        leaves, treedef = jax.tree_util.tree_flatten(template)
        if not leaves:
            raise ValueError("empty parameter tree")
        self.treedef = treedef
        self.shapes = [tuple(x.shape) for x in leaves]
        self.dtypes = [jnp.dtype(x.dtype) for x in leaves]
        self.sizes = [int(math.prod(s)) for s in self.shapes]
        self.num_shards = int(num_shards)
        by_dtype: dict[str, list[int]] = {}
        for i, dt in enumerate(self.dtypes):
            by_dtype.setdefault(dt.name, []).append(i)
        self.groups: dict[str, _Group] = {}
        for name, idxs in sorted(by_dtype.items()):
            offsets, off = {}, 0
            for i in idxs:
                offsets[i] = off
                off += self.sizes[i]
            padded = -(-max(off, 1) // num_shards) * num_shards
            self.groups[name] = _Group(tuple(idxs), offsets, off, padded)

    def pack_flat(self, tree: Pytree) -> dict[str, jnp.ndarray]:
        """Tree -> ``{dtype: [padded]}`` full-length 1-D buffers."""
        leaves = self.treedef.flatten_up_to(tree)
        out = {}
        for name, g in self.groups.items():
            flat = jnp.concatenate(
                [jnp.ravel(leaves[i]) for i in g.indices])
            if g.padded > g.total:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((g.padded - g.total,), flat.dtype)])
            out[name] = flat
        return out

    def pack(self, tree: Pytree) -> dict[str, jnp.ndarray]:
        """Tree -> ``{dtype: [W, block]}`` row-sharded center blocks."""
        return {
            name: flat.reshape(self.num_shards, -1)
            for name, flat in self.pack_flat(tree).items()}

    def unpack(self, flats: Mapping[str, jnp.ndarray]) -> Pytree:
        """``{dtype: [padded]}`` -> tree (inverse of ``pack_flat``)."""
        leaves: list = [None] * len(self.shapes)
        for name, g in self.groups.items():
            flat = flats[name]
            for i in g.indices:
                off = g.offsets[i]
                leaves[i] = flat[off:off + self.sizes[i]].reshape(
                    self.shapes[i])
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def seg_ids(self, name: str) -> np.ndarray:
        """Static ``[padded]`` map position -> group-local leaf
        ordinal; padding tail gets the extra id ``n_leaves`` so it
        never pollutes a leaf's quantization scale."""
        g = self.groups[name]
        ids = np.full((g.padded,), len(g.indices), np.int32)
        for j, i in enumerate(g.indices):
            off = g.offsets[i]
            ids[off:off + self.sizes[i]] = j
        return ids


# ---------------------------------------------------------------------------
# States.
# ---------------------------------------------------------------------------


class MeshPSState(struct.PyTreeNode):
    """Sharded-center PS state.

    ``blocks`` maps dtype name -> ``[W, block]`` packed center rows
    (row *w* lives on worker *w*'s device); ``clock`` is the replicated
    commit clock (same meaning as ``PSState.clock``).
    """

    blocks: Mapping[str, jnp.ndarray]
    clock: jnp.ndarray


class MeshWorkerState(struct.PyTreeNode):
    """``TrainState`` minus ``params``, stacked ``[W, ...]``.

    Between mesh rounds the delta family's worker params are a pure
    function of the center (round-barrier pull), so carrying them
    would re-create exactly the ``[W, params]`` replication this tier
    deletes.
    """

    step: jnp.ndarray
    opt_state: Pytree
    model_state: Mapping[str, Pytree]
    rng: jax.Array


# ---------------------------------------------------------------------------
# The dataplane.
# ---------------------------------------------------------------------------


class MeshDataplane:
    """One compiled SPMD program per PS round (see module docstring).

    Per-round metrics accumulate in a device-resident ring (see
    ``init_ring``/``MeshRoundDriver``), so the signatures are:

    * plain:     ``round(ps, ws, batch, perm, ring, slot)
      -> (ps, ws, ring)``
    * pipelined: ``round(ps, ws, batch, perm, pending, pending_perm,
      pending_valid, ring, slot) -> (ps, ws, pending, perm, valid,
      ring)`` and ``flush(ps, pending, pending_perm) -> ps``

    ``slot`` is a traced replicated int32 scalar (``slot_index(i)``),
    so cycling the ring never retraces.  ``ps``/``ws`` are donated;
    the ring is NOT (old handles stay fetchable for the late metrics
    read).  Convert a host-layout ``(PSState, TrainState)`` pair with
    ``to_device`` once before the first round, and read results back
    via ``center`` / ``export_ps_state``.
    """

    def __init__(self, rule: UpdateRule, step_fn, mesh,
                 center_template: Pytree, *, pipelined: bool = False,
                 partition_rules=DEFAULT_WORKER_RULES,
                 comm_dtype: str = "float32", comm_codec=None,
                 metrics_every: int = 1):
        if rule.payload_kind != "delta":
            raise ValueError(
                "fidelity='mesh' compiles the delta-family commit "
                "(DOWNPOUR/ADAG/DynSGD) into a reduce-scatter; the "
                "elastic family commits absolute params against a "
                "serialized center — use fidelity='faithful' or "
                "'host'")
        if mesh_lib.WORKER_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh has no {mesh_lib.WORKER_AXIS!r} axis: "
                f"{mesh.axis_names}")
        extra = [a for a in mesh.axis_names
                 if a != mesh_lib.WORKER_AXIS and mesh.shape[a] > 1]
        if extra:
            raise ValueError(
                "fidelity='mesh' is data-parallel only (one worker "
                f"per device); mesh has extra axes {extra}")
        if comm_dtype not in COMM_DTYPES:
            raise ValueError(
                f"unknown comm_dtype {comm_dtype!r}; valid: "
                f"{list(COMM_DTYPES)}")
        if comm_codec not in COMM_CODECS:
            raise ValueError(
                f"unknown comm_codec {comm_codec!r}; valid: "
                f"{list(COMM_CODECS)}")
        if int(metrics_every) < 1:
            raise ValueError(
                f"metrics_every must be >= 1, got {metrics_every}")
        self.rule = rule
        self.mesh = mesh
        self.num_workers = int(mesh.shape[mesh_lib.WORKER_AXIS])
        self.pipelined = bool(pipelined)
        self.partition_rules = tuple(partition_rules)
        self.comm_dtype = str(comm_dtype)
        self.comm_codec = comm_codec
        self.metrics_every = int(metrics_every)
        self._window_run = make_window_runner(step_fn)
        self.spec = _FlatSpec(center_template, self.num_workers)
        # compression applies to the float32 groups only — other
        # dtypes (int counters, bool masks) ride uncompressed
        self._quant_groups = frozenset(
            n for n in self.spec.groups
            if comm_codec == "int8" and jnp.dtype(n) == jnp.float32)
        self._bf16_groups = frozenset(
            n for n in self.spec.groups
            if comm_dtype == "bfloat16" and jnp.dtype(n) == jnp.float32)
        self._account_comm_bytes()
        self._rep = mesh_lib.replicated_sharding(mesh)
        self._row = mesh_lib.batch_sharding(mesh)
        self._block_shardings = {n: self._row for n in self.spec.groups}
        self._pack_jit = jax.jit(self.spec.pack,
                                 out_shardings=self._block_shardings)
        self._center_jit = jax.jit(
            lambda mps: self.spec.unpack(
                {n: b.reshape(-1) for n, b in mps.blocks.items()}),
            out_shardings=self._rep)
        self._slot_cache: dict[int, jax.Array] = {}
        self._ws_specs = None  # resolved on first to_device
        # XLA cost ledger: batch-shape key -> (Compiled, record)
        self._programs: dict[tuple, tuple] = {}
        self._cost_records: list[dict] = []
        self._last_record: dict | None = None

    def _account_comm_bytes(self) -> None:
        """Static per-round wire accounting.  Convention: the REMOTE
        fraction each collective moves per device ((W-1)/W of the
        padded buffer), summed over all W devices; the int8 arm adds
        its per-leaf scale ``pmax`` side channel.  ``saved`` is vs the
        all-f32 configuration of the same shapes."""
        W = self.num_workers
        gather = scatter = saved = 0
        for n, g in self.spec.groups.items():
            item = jnp.dtype(n).itemsize
            remote = (g.padded - g.padded // W) * W
            if n in self._quant_groups:
                side = (len(g.indices) + 1) * 4 * W
                gather += remote * 1 + side
                saved += remote * (item - 1) - side
            else:
                gather += remote * item
            if n in self._bf16_groups:
                scatter += remote * 2
                saved += remote * (item - 2)
            else:
                scatter += remote * item
        self.comm_bytes_per_round = {"gather": int(gather),
                                     "scatter": int(scatter)}
        self.comm_bytes_saved_per_round = max(int(saved), 0)

    # -- state conversion ------------------------------------------------

    def to_device(self, ps_state: PSState, worker_states: TrainState
                  ) -> tuple[MeshPSState, MeshWorkerState]:
        """Host/emulated layout -> this tier's sharded layout.

        Must be called once before ``round`` (it also resolves the
        worker partition specs from the concrete state shapes and
        finalizes the compiled programs).
        """
        mws = MeshWorkerState(
            step=worker_states.step, opt_state=worker_states.opt_state,
            model_state=worker_states.model_state,
            rng=worker_states.rng)
        if self._ws_specs is None:
            self._build_programs(mws)
        mws = jax.device_put(mws, self._ws_shardings)
        blocks = self._pack_jit(ps_state.center)
        clock = jax.device_put(jnp.asarray(ps_state.clock), self._rep)
        return MeshPSState(blocks=blocks, clock=clock), mws

    def center(self, mps: MeshPSState) -> Pytree:
        """Replicated center pytree (for eval/export); one compiled
        gather+unpack program, shared by every call."""
        return self._center_jit(mps)

    def export_ps_state(self, mps: MeshPSState) -> PSState:
        """Sharded layout -> the emulated tiers' ``PSState``."""
        return PSState(center=self.center(mps), clock=mps.clock)

    def init_pending(self) -> dict[str, jnp.ndarray]:
        """Zero pending payloads ``{dtype: [W, padded]}`` (inert for
        the delta family until the first round marks them valid)."""
        out = {}
        for name, g in self.spec.groups.items():
            dt = jnp.dtype(name)
            out[name] = jax.device_put(
                jnp.zeros((self.num_workers, g.padded), dt), self._row)
        return out

    def init_ring(self) -> dict[str, jnp.ndarray]:
        """Zero device-resident metrics ring: ``metrics_every`` rounds
        of per-worker ``[W]`` rows per metric.  NOT donated by
        ``round``, so a saved handle from round k stays fetchable
        while round k+1 runs — the async driver's late read."""
        N, W = self.metrics_every, self.num_workers
        ring = {"loss": jnp.zeros((N, W), jnp.float32),
                "grad_norm": jnp.zeros((N, W), jnp.float32),
                "staleness": jnp.zeros((N, W), jnp.int32)}
        return jax.device_put(ring, self._rep)

    def slot_index(self, i: int) -> jax.Array:
        """Replicated traced int32 scalar for ring slot ``i`` (cached:
        one device array per slot, so cycling never re-transfers)."""
        i = int(i) % self.metrics_every
        if i not in self._slot_cache:
            self._slot_cache[i] = jax.device_put(
                jnp.asarray(i, jnp.int32), self._rep)
        return self._slot_cache[i]

    # -- program construction --------------------------------------------

    def _build_programs(self, template: MeshWorkerState) -> None:
        specs = match_partition_rules(self.partition_rules, template)
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        paths = jax.tree_util.tree_flatten_with_path(template)[0]
        spec_leaves = jax.tree_util.tree_leaves(specs, is_leaf=is_spec)
        for (path, leaf), sp in zip(paths, spec_leaves):
            if math.prod(tuple(leaf.shape)) <= 1:
                continue
            if not len(sp) or sp[0] != mesh_lib.WORKER_AXIS:
                raise ValueError(
                    "mesh-tier worker leaves are stacked [W, ...] and "
                    "must shard the leading axis over "
                    f"{mesh_lib.WORKER_AXIS!r}; rule resolved "
                    f"{_path_str(path)!r} to {sp}")
        self._ws_specs = specs
        self._ws_shardings = mesh_lib.shardings_for(self.mesh, specs)

        spec = self.spec
        rule = self.rule
        W = self.num_workers
        WA = mesh_lib.WORKER_AXIS
        dyn = isinstance(rule, DynSGDRule)
        window_run = self._window_run
        row_blocks = {n: P(WA) for n in spec.groups}
        quant = self._quant_groups
        bf16 = self._bf16_groups

        # static per-position leaf ids for the quantized groups, packed
        # [W, block] like the center so each device reads its own row
        self._seg_blocks = {
            n: jax.device_put(
                jnp.asarray(spec.seg_ids(n).reshape(W, -1)), self._row)
            for n in sorted(quant)}
        seg_specs = {n: P(WA) for n in self._seg_blocks}

        def _local(tree):
            return jax.tree_util.tree_map(lambda x: x[0], tree)

        def _stacked(tree):
            return jax.tree_util.tree_map(lambda x: x[None], tree)

        def pull_center(blocks, segs):
            # Fused round-start pull: ONE all-gather per dtype group —
            # the program's only full-center transfer.  Quantized
            # groups gather int8 (per-leaf scales replicated by the
            # pmax, never gathered) and dequantize FUSED into the
            # per-leaf unpack below, so no full-width f32 packed
            # buffer of the center ever materializes.
            flats, scales = {}, {}
            for n, b in blocks.items():
                local = b[0]
                if n in quant:
                    g = spec.groups[n]
                    nseg = len(g.indices)
                    seg = segs[n][0]
                    part = jax.ops.segment_max(
                        jnp.abs(local), seg, num_segments=nseg + 1,
                        indices_are_sorted=True)
                    amax = jax.lax.pmax(part, WA)[:nseg]
                    # the Int8Codec law (quantize_int8), per leaf
                    scale = jnp.where(amax > 0,
                                      amax / jnp.float32(127.0),
                                      jnp.float32(1.0))
                    spos = jnp.concatenate(
                        [scale, jnp.ones((1,), jnp.float32)])[seg]
                    q = jnp.clip(jnp.round(local / spos),
                                 -127.0, 127.0).astype(jnp.int8)
                    flats[n] = jax.lax.all_gather(q, WA, tiled=True)
                    scales[n] = scale
                else:
                    flats[n] = jax.lax.all_gather(b[0], WA, tiled=True)
            leaves: list = [None] * len(spec.shapes)
            for n, g in spec.groups.items():
                flat, sc = flats[n], scales.get(n)
                for j, i in enumerate(g.indices):
                    off = g.offsets[i]
                    piece = flat[off:off + spec.sizes[i]]
                    if sc is not None:
                        piece = piece.astype(jnp.float32) * sc[j]
                    leaves[i] = piece.reshape(spec.shapes[i])
            return jax.tree_util.tree_unflatten(spec.treedef, leaves)

        def window_and_delta(blocks, segs, ws, batch):
            center = pull_center(blocks, segs)
            state = TrainState(
                step=ws.step[0], params=center,
                opt_state=_local(ws.opt_state),
                model_state=_local(ws.model_state), rng=ws.rng[0])
            local_batch = _local(batch)
            window = jax.tree_util.tree_leaves(
                local_batch)[0].shape[0]
            new_state, step_metrics = window_run(state, local_batch)
            # delta vs the center this worker actually SAW (the
            # dequantized pull under comm_codec) — commits fold into
            # the exact shards, so the server never drifts lossily
            delta = rule.normalize_delta(
                utils.tree_sub(new_state.params, center), window)
            new_ws = MeshWorkerState(
                step=new_state.step[None],
                opt_state=_stacked(new_state.opt_state),
                model_state=_stacked(new_state.model_state),
                rng=new_state.rng[None])
            return spec.pack_flat(delta), new_ws, step_metrics

        def commit(blocks, flat, scale):
            # Per-device scaled payload -> reduce-scatter -> each
            # device folds the reduction into its own center shard.
            # bf16 groups ride the wire (and reduce) narrowed — the
            # Bf16Codec cast law; the shard itself stays f32.
            out = {}
            for n, b in blocks.items():
                payload = flat[n] * scale.astype(flat[n].dtype)
                if n in bf16:
                    red = jax.lax.psum_scatter(
                        payload.astype(jnp.bfloat16), WA,
                        tiled=True).astype(b.dtype)
                else:
                    red = jax.lax.psum_scatter(payload, WA, tiled=True)
                out[n] = b + red[None]
            return out

        def round_body(blocks, segs, clock, ws, batch, inv):
            flat, new_ws, sm = window_and_delta(blocks, segs, ws, batch)
            pos = inv[jax.lax.axis_index(WA)]
            scale = (1.0 / (pos.astype(jnp.float32) + 1.0) if dyn
                     else jnp.float32(1.0))
            new_blocks = commit(blocks, flat, scale)
            metrics = {
                "loss": sm["loss"].mean()[None],
                "grad_norm": sm["grad_norm"].mean()[None],
                "staleness": pos.astype(jnp.int32)[None],
            }
            return new_blocks, clock + W, new_ws, metrics

        round_smap = utils.shard_map(
            round_body, mesh=self.mesh,
            in_specs=(row_blocks, seg_specs, P(), specs, P(WA), P()),
            out_specs=(row_blocks, P(), specs, P(WA)))

        rep = self._rep

        def write_ring(ring, slot, metrics):
            # Pin the updated ring to the replicated sharding of
            # ``init_ring`` — GSPMD would otherwise propagate the
            # metric rows' worker sharding into the output, giving
            # round k+1 a different input signature than round k and
            # breaking the one-executable-per-shape AOT ledger.
            return {k: jax.lax.with_sharding_constraint(
                        ring[k].at[slot].set(
                            metrics[k].astype(ring[k].dtype)), rep)
                    for k in ring}

        def plain_round(mps, mws, batch, perm, ring, slot):
            # Python side effect at TRACE time only — the public
            # one-compile-per-(round-shape x comm-config) guard (same
            # contract as the emulated tiers' counter).
            telemetry.metrics().counter(
                "ps_round_compiles_total", fidelity="mesh").inc()
            inv = jnp.argsort(perm)
            blocks, clock, ws, metrics = round_smap(
                mps.blocks, self._seg_blocks, mps.clock, mws, batch,
                inv)
            return (MeshPSState(blocks=blocks, clock=clock), ws,
                    write_ring(ring, slot, metrics))

        def pipe_body(blocks, segs, clock, ws, batch, inv, pending,
                      pinv, pvalid):
            # window k (on the pre-commit center) and the commit of
            # round k-1's pending are independent subgraphs — XLA
            # overlaps them, same contract as make_pipelined_round_fn.
            flat, new_ws, sm = window_and_delta(blocks, segs, ws, batch)
            pos = inv[jax.lax.axis_index(WA)]
            ppos = pinv[jax.lax.axis_index(WA)]
            pscale = (1.0 / (ppos.astype(jnp.float32) + W + 1.0)
                      if dyn else jnp.float32(1.0))
            pscale = pscale * pvalid.astype(jnp.float32)
            new_blocks = commit(
                blocks, {n: p[0] for n, p in pending.items()}, pscale)
            new_clock = clock + W * pvalid.astype(clock.dtype)
            metrics = {
                "loss": sm["loss"].mean()[None],
                "grad_norm": sm["grad_norm"].mean()[None],
                # true commit depth: one full round behind + position
                "staleness": (pos + W).astype(jnp.int32)[None],
            }
            new_pending = {n: f[None] for n, f in flat.items()}
            return (new_blocks, new_clock, new_ws, metrics,
                    new_pending, jnp.asarray(True))

        pipe_smap = utils.shard_map(
            pipe_body, mesh=self.mesh,
            in_specs=(row_blocks, seg_specs, P(), specs, P(WA), P(),
                      {n: P(WA) for n in spec.groups}, P(), P()),
            out_specs=(row_blocks, P(), specs, P(WA),
                       {n: P(WA) for n in spec.groups}, P()))

        def pipe_round(mps, mws, batch, perm, pending, pending_perm,
                       pending_valid, ring, slot):
            telemetry.metrics().counter(
                "ps_round_compiles_total",
                fidelity="mesh_pipelined").inc()
            inv = jnp.argsort(perm)
            pinv = jnp.argsort(pending_perm)
            (blocks, clock, ws, metrics, new_pending,
             valid) = pipe_smap(mps.blocks, self._seg_blocks,
                                mps.clock, mws, batch, inv, pending,
                                pinv, pending_valid)
            return (MeshPSState(blocks=blocks, clock=clock), ws,
                    new_pending, perm, valid,
                    write_ring(ring, slot, metrics))

        def flush_body(blocks, clock, pending, pinv):
            # drain at TRUE depth: no window ran ahead -> offset 0
            ppos = pinv[jax.lax.axis_index(WA)]
            scale = (1.0 / (ppos.astype(jnp.float32) + 1.0) if dyn
                     else jnp.float32(1.0))
            new_blocks = commit(
                blocks, {n: p[0] for n, p in pending.items()}, scale)
            return new_blocks, clock + W

        flush_smap = utils.shard_map(
            flush_body, mesh=self.mesh,
            in_specs=(row_blocks, P(),
                      {n: P(WA) for n in spec.groups}, P()),
            out_specs=(row_blocks, P()))

        def flush_fn(mps, pending, pending_perm):
            pinv = jnp.argsort(pending_perm)
            blocks, clock = flush_smap(mps.blocks, mps.clock, pending,
                                       pinv)
            return MeshPSState(blocks=blocks, clock=clock)

        if self.pipelined:
            round_jit = jax.jit(pipe_round, donate_argnums=(0, 1, 4))
            self.flush = jax.jit(flush_fn, donate_argnums=(0, 1))
            fid = "mesh_pipelined"
        else:
            round_jit = jax.jit(plain_round, donate_argnums=(0, 1))
            fid = "mesh"
        self._round_jit = round_jit
        self._round_fid = fid
        saved = self.comm_bytes_saved_per_round
        programs = self._programs

        def dispatch_round(*args):
            # host-side wire accounting per dispatched round (static
            # bytes, from the packed shapes) — ~200ns when telemetry
            # is disabled, invisible next to the device round
            if saved:
                telemetry.metrics().counter(
                    "ps_round_comm_bytes_saved_total",
                    fidelity=fid).inc(saved)
            # AOT execution path: one explicit lower+compile per batch
            # shape (args[2]; every other operand's shape is fixed per
            # dataplane), so the cost ledger holds the Compiled handle
            # for EVERY program that ever runs — same one-trace-per-
            # shape contract the compile guard asserts, plus
            # cost/memory analysis and compile time on the record.
            key = tuple((tuple(x.shape), str(x.dtype))
                        for x in jax.tree_util.tree_leaves(args[2]))
            entry = programs.get(key)
            if entry is None:
                entry = self._compile_round(key, args)
            self._last_record = entry[1]
            return entry[0](*args)

        self.round = dispatch_round

    def _compile_round(self, key, args):
        """Ledger miss: AOT-compile the round for this batch shape and
        record its XLA cost model (tentpole 1, ISSUE 17)."""
        from distkeras_tpu import attrib as attrib_lib

        fid = self._round_fid
        t0 = time.perf_counter()
        compiled = self._round_jit.lower(*args).compile()
        compile_s = time.perf_counter() - t0
        cost = attrib_lib.extract_cost(compiled)
        rec = {
            "program": fid,
            "comm_dtype": self.comm_dtype,
            "comm_codec": self.comm_codec,
            "workers": self.num_workers,
            "batch_shapes": key,
            "flops": cost["flops"],
            "bytes_accessed": cost["bytes_accessed"],
            "peak_temp_bytes": cost["peak_temp_bytes"],
            "argument_bytes": cost["argument_bytes"],
            "output_bytes": cost["output_bytes"],
            "collective_bytes": dict(self.comm_bytes_per_round),
            "comm_bytes_saved": self.comm_bytes_saved_per_round,
            "compile_s": compile_s,
        }
        m = telemetry.metrics()
        m.counter("ps_round_compile_seconds_total",
                  fidelity=fid).inc(compile_s)
        if cost["flops"] is not None:
            m.gauge("ps_round_program_flops", fidelity=fid).set(
                cost["flops"])
        if cost["bytes_accessed"] is not None:
            m.gauge("ps_round_program_bytes_accessed",
                    fidelity=fid).set(cost["bytes_accessed"])
        self._cost_records.append(rec)
        entry = (compiled, rec)
        self._programs[key] = entry
        return entry

    def last_program_record(self) -> dict | None:
        """Ledger record of the most recently dispatched program (the
        driver's sampled MFU pair reads per-device flops off it)."""
        return self._last_record

    def cost_report(self) -> list[dict]:
        """The XLA cost ledger: one record per compiled round program
        (per batch shape; a dataplane instance is already per comm
        config), with the roofline prediction appended against the
        local device's peak numbers.

        Record schema: ``program`` (fidelity), ``comm_dtype`` /
        ``comm_codec`` / ``workers`` / ``batch_shapes`` (config),
        ``flops`` / ``bytes_accessed`` / ``peak_temp_bytes`` (XLA cost
        + memory analysis, per device; ``None`` when the backend hides
        them), ``collective_bytes`` / ``comm_bytes_saved`` (static wire
        accounting), ``compile_s``, and ``roofline`` (``t_compute_s`` /
        ``t_comm_s`` / ``t_roofline_s`` / ``bound`` /
        ``arithmetic_intensity`` per :func:`attrib.roofline`) with the
        ``peak_flops`` / ``peak_bytes_per_sec`` / ``peak_known`` terms
        it was computed against.
        """
        from distkeras_tpu import attrib as attrib_lib
        from distkeras_tpu import profiling

        dev = jax.devices()[0]
        peak, peak_known = profiling.peak_flops(dev)
        bw, bw_known = profiling.peak_bandwidth(dev)
        out = []
        for rec in self._cost_records:
            r = dict(rec)
            per_dev_comm = (sum(rec["collective_bytes"].values())
                            / max(rec["workers"], 1))
            r["roofline"] = attrib_lib.roofline(
                rec["flops"] or 0.0, per_dev_comm, peak, bw)
            r["peak_flops"] = peak
            r["peak_bytes_per_sec"] = bw
            r["peak_known"] = bool(peak_known and bw_known)
            out.append(r)
        return out


# ---------------------------------------------------------------------------
# Async host dispatch.
# ---------------------------------------------------------------------------


class MeshRoundDriver:
    """Host loop for the mesh round: dispatch k+1 before fetching k.

    Owns the dataplane state (``mps``/``mws``, plus the pipelined
    variant's pending commit) and the metrics ring.  ``dispatch``
    enqueues one round and NEVER blocks on device results; a completed
    ring (every ``metrics_every`` rounds) is fetched only after at
    least one newer round has been dispatched, so host control never
    serializes the device.  ``metrics_every=1`` with async fetch
    reproduces the trainer's historical one-round-late drain exactly.

    ``sync=True`` fetches eagerly after every dispatch — the test
    oracle the async path is asserted byte-identical against.

    ``poll()`` returns per-round metric dicts (host numpy, ``[W]`` per
    metric) that became available since the last call, in round order;
    ``drain()`` additionally blocks on everything outstanding
    (including a partially filled ring) and resets the ring cursor.
    Each device read of a ring increments
    ``ps_metrics_fetches_total``.

    ``attrib_every=N`` arms the sampled step-time decomposition (ISSUE
    17 tentpole 2): every Nth dispatched round is split into host_gap /
    dispatch / device_compute / ring_fetch segments
    (``ps_round_attrib_seconds_total{segment}``) and pairs the
    observed MFU against the ledger's roofline prediction
    (``mfu_observed`` / ``mfu_roofline`` gauges; the latest sample is
    also kept on ``last_attrib`` so bench records work with telemetry
    off).  A sampled round serializes host on device — it is a
    measurement, not the fast path — while non-sampled rounds pay only
    the ``_attrib_tick`` guard plus one clock stamp, and
    ``attrib_every=0`` (default) pays a single int test
    (``attrib.attrib_overhead`` bounds both).  Sampling only ever adds
    reads (an extra block + ring fetch), so the trained state is
    byte-identical to an attrib-off run.
    """

    def __init__(self, dp: MeshDataplane, mps: MeshPSState,
                 mws: MeshWorkerState, *, sync: bool = False,
                 attrib_every: int = 0):
        self.dp = dp
        self.mps = mps
        self.mws = mws
        self.sync = bool(sync)
        self.attrib_every = int(attrib_every)
        if self.attrib_every < 0:
            raise ValueError("attrib_every must be >= 0 (0 disables "
                             "round attribution sampling)")
        self.ring = dp.init_ring()
        self._slot = 0          # next ring slot to write
        self._emitted = 0       # current-ring slots already emitted
        self._round_index = 0   # total rounds dispatched (attrib clock)
        self._last_end = None   # host-gap anchor: prior dispatch end
        self._peaks = None      # cached (peak_flops, known) per driver
        self.last_attrib: dict | None = None
        self._queued: collections.deque = collections.deque()
        self._ready: list[dict] = []
        if dp.pipelined:
            self.pending = dp.init_pending()
            self.pending_perm = jax.device_put(
                jnp.arange(dp.num_workers, dtype=jnp.int32), dp._rep)
            self._false = jax.device_put(jnp.asarray(False), dp._rep)
            self.pending_valid = self._false
            self.pend_live = False

    def _attrib_tick(self) -> bool:
        """Fast-path sampling guard: is the round about to be
        dispatched a sampled one?  ``attrib_every=0`` exits on one int
        test; armed it adds one modulo — the whole disabled-path cost
        ``attrib.attrib_overhead`` bounds (plus the end-of-dispatch
        clock stamp when armed)."""
        ae = self.attrib_every
        if not ae:
            return False
        return self._round_index % ae == 0

    def dispatch(self, batch, perm) -> None:
        """Enqueue one round; fetch only rings completed BEFORE this
        dispatch (async) or everything so far (sync)."""
        sampled = self._attrib_tick()
        self._round_index += 1
        if sampled:
            t0 = time.perf_counter()
        ready = list(self._queued)
        self._queued.clear()
        slot = self.dp.slot_index(self._slot)
        if self.dp.pipelined:
            (self.mps, self.mws, self.pending, self.pending_perm,
             self.pending_valid, self.ring) = self.dp.round(
                self.mps, self.mws, batch, perm, self.pending,
                self.pending_perm, self.pending_valid, self.ring, slot)
            self.pend_live = True
        else:
            self.mps, self.mws, self.ring = self.dp.round(
                self.mps, self.mws, batch, perm, self.ring, slot)
        self._slot += 1
        if sampled:
            self._sample(t0)
        elif self.attrib_every:
            self._last_end = time.perf_counter()
        if self.sync:
            # eager oracle: read the just-written slot every round
            self._emit(self.ring, self._emitted, self._slot)
            self._emitted = self._slot
            if self._slot == self.dp.metrics_every:
                self._slot = self._emitted = 0
        else:
            if self._slot == self.dp.metrics_every:
                self._queued.append((self.ring, self._slot))
                self._slot = 0
            for ring, count in ready:
                self._emit(ring, 0, count)

    def _sample(self, t0: float) -> None:
        """Sampled-round decomposition: split the just-dispatched round
        into segments, emit counters/gauges, stash ``last_attrib``.

        Segments: ``host_gap`` (end of previous dispatch -> this
        dispatch start: host-side work between rounds), ``dispatch``
        (enqueue: program-cache hit + runtime dispatch), and — read off
        the SAME in-flight round by serializing on it — ``device_compute``
        (enqueue return -> outputs ready) and ``ring_fetch`` (device ->
        host transfer of the metrics ring).  The extra block/fetch only
        READS; the trained state is untouched.
        """
        t1 = time.perf_counter()
        jax.block_until_ready((self.mps.blocks, self.ring))
        t2 = time.perf_counter()
        jax.device_get(self.ring)
        t3 = time.perf_counter()
        seg = {
            "host_gap": (t0 - self._last_end
                         if self._last_end is not None else 0.0),
            "dispatch": t1 - t0,
            "device_compute": t2 - t1,
            "ring_fetch": t3 - t2,
        }
        m = telemetry.metrics()
        for name, secs in seg.items():
            m.counter("ps_round_attrib_seconds_total",
                      segment=name).inc(secs)
        attrib = dict(seg)
        rec = self.dp.last_program_record()
        if rec is not None and rec.get("flops"):
            from distkeras_tpu import attrib as attrib_lib
            from distkeras_tpu import profiling

            if self._peaks is None:
                dev = jax.devices()[0]
                self._peaks = (profiling.peak_flops(dev),
                               profiling.peak_bandwidth(dev))
            (peak, peak_known), (bw, bw_known) = self._peaks
            per_dev_comm = (sum(rec["collective_bytes"].values())
                            / max(rec["workers"], 1))
            roof = attrib_lib.roofline(rec["flops"], per_dev_comm,
                                       peak, bw)
            # observed round time = enqueue + device execution: on an
            # async backend dispatch is ~0 so this IS device time; on
            # the synchronous CPU backend the round runs inside the
            # enqueue call and device_compute alone would be ~0
            obs = attrib_lib.mfu(
                rec["flops"],
                seg["dispatch"] + seg["device_compute"], peak)
            pred = attrib_lib.mfu(rec["flops"], roof["t_roofline_s"],
                                  peak)
            if obs is not None and pred is not None:
                m.gauge("mfu_observed").set(obs)
                m.gauge("mfu_roofline").set(pred)
                attrib["mfu_observed"] = obs
                attrib["mfu_roofline"] = pred
                attrib["peak_known"] = bool(peak_known and bw_known)
                attrib["roofline"] = roof
        self.last_attrib = attrib
        self._last_end = time.perf_counter()

    def _emit(self, ring, start: int, stop: int) -> None:
        telemetry.metrics().counter("ps_metrics_fetches_total").inc()
        host = jax.device_get(ring)
        for r in range(start, stop):
            self._ready.append({k: v[r] for k, v in host.items()})

    def poll(self) -> list[dict]:
        """Metric dicts that became available since the last call."""
        out, self._ready = self._ready, []
        return out

    def drain(self) -> list[dict]:
        """Block on every outstanding metric (full + partial rings),
        reset the ring cursor, and return them in round order."""
        while self._queued:
            ring, count = self._queued.popleft()
            self._emit(ring, 0, count)
        if self._slot > self._emitted:
            self._emit(self.ring, self._emitted, self._slot)
        self._slot = self._emitted = 0
        return self.poll()

    def flush_pipeline(self) -> None:
        """Pipelined variant: fold the carried pending commit into the
        center (epoch end / end of training) and re-arm a fresh inert
        pending (the flushed buffers were donated)."""
        if not self.dp.pipelined or not self.pend_live:
            return
        self.mps = self.dp.flush(self.mps, self.pending,
                                 self.pending_perm)
        self.pending = self.dp.init_pending()
        self.pending_valid = self._false
        self.pend_live = False

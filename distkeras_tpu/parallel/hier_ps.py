"""Hierarchical two-level PS aggregation (ISSUE 20, ROADMAP item 4a).

The flat socket PS serializes every worker's push on ONE commit path;
PERF.md §25 measured the single-mutex server *degrading* as workers
grow, and the sharded PS only spreads — never shrinks — the fan-in.
This module adds the tree-aggregation shape every production PS stack
converges on: leaf groups of G workers commit to a local
:class:`GroupLeader`, which folds their delta payloads over an
``aggregate_window`` with the rule's own closed-form server law and
forwards ONE pre-reduced upstream commit per window, so the root pays
O(groups) commits per round instead of O(workers).

Fold law.  The leader keeps a zero-initialized accumulator and applies
each worker commit with the rule's OWN ``commit`` against that
accumulator as the center::

    fold <- rule.commit(PSState(center=fold, clock), payload, staleness)

For the delta family this is exactly ``fold += scale(staleness) *
payload`` (scale = 1 for DOWNPOUR/ADAG, ``1/(staleness+1)`` for
DynSGD), so the root's plain ``center += fold`` reproduces the flat
server's arithmetic; the per-worker staleness vector rides the
upstream frame so the root's staleness bookkeeping (log + histogram)
stays faithful.  Staleness is leader-local: the leader's commit clock
minus the worker's last pull clock at the leader — the same law the
flat server applies, evaluated where the contention actually is.
Floating-point reassociation caveat: the fold reassociates the round's
additions like any tree reduction; byte-identity with the flat
topology holds whenever the payload sums are exact (the parity tests
use dyadic-rational payloads), and to ~1 ulp otherwise.

Durability contract.  A leader's ack means the commit is FOLDED, not
yet durable at the root: at most ``aggregate_window - 1`` acked
commits ride in the open window and die with a crashed leader (the
degraded-not-down tradeoff; set ``aggregate_window=1`` for flat-PS
durability at flat-PS fan-in).  The leader's own upstream retry is
exactly-once: the root dedupes per-leader upstream seqs
(``commit_group``), so a lost-ack resend never double-applies a
window.  Leader death is handled client-side: :class:`LeaderRoute`
fails workers over to direct-to-root mode (``leader_down`` /
``leader_rejoin`` flight kinds, ``ps_leader_failovers_total``).

Wire.  One new ``"hier"``-scope op on the existing ``transport``
framing, gather-sent (no join copy)::

    upstream_commit := op + seq(8B BE) + n(2B BE)
                       + n * (worker_id(4B BE) + staleness(4B BE))
                       + pack_params(fold)          -> pack_params(center)

Leaders identify themselves on the root hello with worker ids from
``HIER_LEADER_BASE + group_id`` — a distinct id space, so root-side
dedupe keyed by leader can never collide with a real worker's seqs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

import numpy as np

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.host_ps import (
    _NO_SEQ,
    _readonly_tree,
    _ReplicaCycler,
    _to_numpy,
    pack_params,
    PSClient,
    PSServer,
    ResilientPSClient,
    unpack_params,
)
from distkeras_tpu.parallel.update_rules import PSState, UpdateRule
from distkeras_tpu.utils import tree_add, tree_zeros_like

Pytree = Any

#: leader hello ids start here: far above any worker id, below the
#: reserved probe id (2**32 - 1), so root-side per-leader dedupe and
#: liveness bookkeeping can never collide with a real worker's.
HIER_LEADER_BASE = 2 ** 31

# the one "hier"-scope wire op (registered in transport.WIRE_OPS)
_OP_UPSTREAM = b"u"


class HierPSServer(PSServer):
    """Root-side TCP front end: the classic PS protocol plus the
    ``upstream_commit`` op, dispatched to ``ps.commit_group`` (both
    ``HostParameterServer`` and ``ShardedParameterServer`` implement
    it).  Direct-to-root workers keep speaking the classic verbs on
    the same port — the degraded mode after a leader death."""

    def _dispatch(self, conn, worker_id, codec, cmd, body, rx, tx):
        if cmd == _OP_UPSTREAM:
            seq = int.from_bytes(body[:8], "big")
            if seq == _NO_SEQ:
                seq = None
            n = int.from_bytes(body[8:10], "big")
            off = 10
            workers, staleness = [], []
            for _ in range(n):
                workers.append(int.from_bytes(body[off:off + 4],
                                              "big"))
                staleness.append(int.from_bytes(body[off + 4:off + 8],
                                                "big"))
                off += 8
            fold = unpack_params(self._template, body[off:])
            pulled = self.ps.commit_group(worker_id, fold, staleness,
                                          workers, seq=seq)
            wire = pack_params(pulled, self._template)
            tx.inc(len(wire))
            transport.send_msg(conn, wire)
        else:
            super()._dispatch(conn, worker_id, codec, cmd, body, rx,
                              tx)


class _UpstreamLink:
    """The leader's single connection to the root ``HierPSServer``:
    lazy connect, bounded reconnect-and-resend retry.  A resend reuses
    the SAME upstream seq, so a window whose *ack* was lost dedupes at
    the root instead of applying twice (exactly-once end to end)."""

    def __init__(self, host: str, port: int, leader_id: int,
                 template: Pytree, *, retries: int = 10,
                 backoff: float = 0.05):
        self._addr = (str(host), int(port))
        self._leader_id = int(leader_id)
        self._template = template
        self._retries = int(retries)
        self._backoff = float(backoff)
        self._sock = None

    def _connect(self):
        self._sock = transport.connect(*self._addr, timeout=30.0)
        transport.send_msg(
            self._sock, int(self._leader_id).to_bytes(4, "big"))

    def exchange(self, seq: int, constituents, fold_packed: bytes
                 ) -> Pytree:
        """Send one upstream window, return the root's new center."""
        head = (_OP_UPSTREAM + int(seq).to_bytes(8, "big")
                + len(constituents).to_bytes(2, "big")
                + b"".join(int(w).to_bytes(4, "big")
                           + int(s).to_bytes(4, "big")
                           for w, s in constituents))
        last: Exception | None = None
        for attempt in range(self._retries + 1):
            try:
                if self._sock is None:
                    self._connect()
                transport.send_msg_gather(self._sock, head,
                                          fold_packed)
                reply = transport.recv_msg(self._sock)
                return unpack_params(self._template, reply)
            except (ConnectionError, OSError) as e:
                last = e
                self.close()
                if attempt < self._retries:
                    time.sleep(self._backoff * (attempt + 1))
        raise ConnectionError(
            f"upstream commit seq={seq} failed after "
            f"{self._retries + 1} attempts against "
            f"{self._addr}") from last

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class GroupLeader:
    """One aggregation-tier node: fronts G workers with the SAME
    server face (and ``PSServer`` wire) as a flat PS, but commits land
    in a window accumulator instead of a center; every
    ``aggregate_window``-th commit (or, with ``flush_interval``, a
    clock-based timeout on a partial window) flushes ONE pre-reduced
    upstream commit to the root and adopts the returned center as the
    new local mirror.

    Workers pull ``mirror + fold`` — the freshest center view this
    leader can serve without a root round trip; commit replies are the
    same local ack, which is where the throughput win comes from
    (G - 1 of every G commits never wait on the root).

    Delta family only: a params-kind payload (elastic rules) has no
    meaningful sum, so construction rejects it."""

    def __init__(self, rule: UpdateRule, template: Pytree,
                 upstream: tuple[str, int], *, group_id: int = 0,
                 aggregate_window: int = 1,
                 flush_interval: float | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 upstream_retries: int = 10):
        if rule.payload_kind != "delta":
            raise ValueError(
                f"hierarchical aggregation needs a delta-family rule; "
                f"{type(rule).__name__} commits "
                f"{rule.payload_kind!r} payloads")
        if int(aggregate_window) < 1:
            raise ValueError(
                f"aggregate_window must be >= 1, got "
                f"{aggregate_window}")
        self.rule = rule
        self.group_id = int(group_id)
        self.leader_id = HIER_LEADER_BASE + self.group_id
        self.aggregate_window = int(aggregate_window)
        self.flush_interval = (None if flush_interval is None
                               else float(flush_interval))
        self._template = _to_numpy(template)
        self._upstream = _UpstreamLink(
            upstream[0], upstream[1], self.leader_id, self._template,
            retries=upstream_retries)
        self._lock = racecheck.lock("hier_leader")
        # serializes upstream flushes: seqs are assigned AND sent under
        # this lock, so the root never sees seq k+1 before k (its
        # dedupe would otherwise drop the late window as a duplicate)
        self._flush_lock = racecheck.lock("hier_leader.flush")
        self._mirror = _to_numpy(template)  # guarded-by: _lock
        self._fold = tree_zeros_like(self._template)  # guarded-by: _lock
        self._constituents: list[tuple[int, int]] = []
        self._window_opened: float | None = None  # guarded-by: _lock
        self._clock = 0  # guarded-by: _lock
        self._pull_clock: dict[int, int] = {}
        self._last_seen: dict[int, float] = {}
        self._last_reply: dict[int, tuple[int, bytes]] = {}
        self._up_seq = 0  # guarded-by: _flush_lock
        self.num_commits = 0
        self.num_upstream = 0
        self.epoch = 0
        self.server = PSServer(self, self._template, host=host,
                               port=port)
        self._stop_timer = threading.Event()
        self._timer: threading.Thread | None = None
        if self.flush_interval is not None:
            self._timer = threading.Thread(target=self._timer_loop,
                                           daemon=True)

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> tuple[str, int]:
        return self.server.address

    def start(self) -> "GroupLeader":
        self.server.start()
        if self._timer is not None:
            self._timer.start()
        return self

    def stop(self):
        """Plain teardown (no flush — call ``drain()`` first if the
        open window must reach the root)."""
        self._stop_timer.set()
        if self._timer is not None:
            self._timer.join()
        self.server.stop()
        self._upstream.close()

    def kill(self):
        """Crash simulation: drop the worker-facing sockets AND the
        upstream link mid-window — the open window's folded commits
        die with the leader (the documented durability tradeoff);
        workers see ``ConnectionError`` and fail over to the root."""
        self._stop_timer.set()
        self.server.kill()
        self._upstream.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- the server face PSServer dispatches against -----------------------

    def pull(self, worker_id: int) -> Pytree:
        telemetry.metrics().counter("ps_pulls_total").inc()
        with self._lock:
            self._pull_clock[worker_id] = self._clock
            self._last_seen[worker_id] = telemetry.now()
            return _readonly_tree(
                _to_numpy(tree_add(self._mirror, self._fold)))

    def commit(self, worker_id: int, payload: Pytree,
               local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        """Fold one worker commit into the open window and ack
        locally; the commit that fills the window carries the flush
        (synchronously, outside the state lock) before returning."""
        del local  # delta family only — pull law never reads it
        payload = _to_numpy(payload)
        m = telemetry.metrics()
        flush_out = None
        with self._lock:
            if seq is not None:
                last = self._last_reply.get(worker_id)
                if last is not None and seq <= last[0]:
                    self._last_seen[worker_id] = telemetry.now()
                    m.counter("ps_commit_dedup_total").inc()
                    # lint: allow(blocking-call-under-lock): acked =>
                    # recorded, same contract as the flat server
                    flight_recorder.record("commit_dedup",
                                           worker=worker_id, seq=seq)
                    return unpack_params(self._template, last[1])
            staleness = self._clock - self._pull_clock.get(worker_id,
                                                          0)
            state = PSState(center=self._fold,
                            clock=np.int32(self._clock))
            self._fold = _to_numpy(self.rule.commit(
                state, payload, np.int32(staleness)).center)
            self._clock += 1
            self._pull_clock[worker_id] = self._clock
            if not self._constituents:
                self._window_opened = telemetry.now()
            self._constituents.append((int(worker_id),
                                       int(staleness)))
            self.num_commits += 1
            self._last_seen[worker_id] = telemetry.now()
            pulled = _to_numpy(tree_add(self._mirror, self._fold))
            if seq is not None:
                self._last_reply[worker_id] = (seq,
                                               pack_params(pulled))
            if len(self._constituents) >= self.aggregate_window:
                flush_out = (self._fold, self._constituents)
                self._fold = tree_zeros_like(self._template)
                self._constituents = []
                self._window_opened = None
        if flush_out is not None:
            self._flush(*flush_out)
        return _readonly_tree(pulled)

    def register(self, worker_id: int) -> None:
        with self._lock:
            self._last_seen.setdefault(worker_id, telemetry.now())

    def retire(self, worker_id: int) -> None:
        with self._lock:
            self._last_seen.pop(worker_id, None)
            self._last_reply.pop(worker_id, None)

    def idle_workers(self, timeout: float) -> list[int]:
        now = telemetry.now()
        with self._lock:
            return sorted(w for w, seen in self._last_seen.items()
                          if now - seen > timeout)

    def clear_reply_cache(self) -> None:
        with self._lock:
            self._last_reply.clear()

    @property
    def center(self) -> Pytree:
        """The leader's center view: mirror + open fold."""
        with self._lock:
            return _readonly_tree(
                _to_numpy(tree_add(self._mirror, self._fold)))

    # -- upstream ----------------------------------------------------------

    def drain(self) -> None:
        """Flush any open partial window and wait until every
        in-flight upstream exchange has been acked by the root — after
        this returns, every folded commit is durable upstream (called
        before final-center reads and clean shutdown)."""
        with self._lock:
            flush_out = None
            if self._constituents:
                flush_out = (self._fold, self._constituents)
                self._fold = tree_zeros_like(self._template)
                self._constituents = []
                self._window_opened = None
        if flush_out is not None:
            self._flush(*flush_out)
        else:
            with self._flush_lock:
                pass  # barrier: an in-flight flush holds this lock

    def _flush(self, fold: Pytree, constituents) -> None:
        with self._flush_lock:
            seq = self._up_seq
            self._up_seq += 1
            with telemetry.span("hier_aggregate",
                                group=self.group_id, seq=seq,
                                fanin=len(constituents)):
                packed = pack_params(fold, self._template)
                center = self._upstream.exchange(seq, constituents,
                                                 packed)
            with self._lock:
                self._mirror = _to_numpy(center)
                self.num_upstream += 1

    def _timer_loop(self):
        poll = max(self.flush_interval / 4, 0.001)
        while not self._stop_timer.wait(poll):
            flush_out = None
            with self._lock:
                opened = self._window_opened
                if (self._constituents and opened is not None
                        and telemetry.now() - opened
                        >= self.flush_interval):
                    flush_out = (self._fold, self._constituents)
                    self._fold = tree_zeros_like(self._template)
                    self._constituents = []
                    self._window_opened = None
            if flush_out is not None:
                try:
                    self._flush(*flush_out)
                except (ConnectionError, OSError):
                    return  # root gone: the drain/stop path reports it


class LeaderRoute(_ReplicaCycler):
    """Two-address failover route: the group's leader first, the root
    as the degraded fallback.  Advancing off the leader records a
    ``leader_down`` flight event and bumps
    ``ps_leader_failovers_total`` (the ``leader_failover_rate`` SLO's
    numerator); a later successful build back at the leader address
    records ``leader_rejoin``.  Probe-before-advance is inherited: a
    chaos-injected transient on a healthy leader retries in place
    instead of stampeding the root."""

    def __init__(self, leader: tuple[str, int], root: tuple[str, int],
                 *, worker: int | None = None,
                 probe_timeout: float = 0.25):
        super().__init__([leader, root], worker=worker,
                         probe_timeout=probe_timeout)
        self._degraded = False  # guarded-by: _lock

    def connect(self, build: Callable[[str, int], Any]):
        try:
            client = super().connect(build)
        except Exception:
            with self._lock:
                went_down = self._i == 1 and not self._degraded
                if went_down:
                    self._degraded = True
            if went_down:
                telemetry.metrics().counter(
                    "ps_leader_failovers_total").inc()
                flight_recorder.record(
                    "leader_down", worker=self.worker,
                    leader_port=self.addresses[0][1])
            raise
        with self._lock:
            rejoined = self._degraded and self._i == 0
            if rejoined:
                self._degraded = False
        if rejoined:
            flight_recorder.record(
                "leader_rejoin", worker=self.worker,
                leader_port=self.addresses[0][1])
        return client


def resilient_hier_client(leader: tuple[str, int],
                          root: tuple[str, int], *, worker_id: int,
                          template: Pytree, codec=None,
                          **kw) -> ResilientPSClient:
    """A grouped worker's client: ``ResilientPSClient`` over a
    :class:`LeaderRoute`, so a dead leader degrades the worker to
    direct-to-root mode within one retry (and back, when the route
    wraps to a revived leader).  The route rides on ``.replicas`` —
    the same attribute ``for_replicas`` uses — so callers fold
    ``.failovers`` into history identically."""
    route = LeaderRoute(leader, root, worker=worker_id)

    def factory():
        return route.connect(
            lambda h, p: PSClient(h, p, worker_id, template,
                                  codec=codec))

    client = ResilientPSClient(factory, worker=worker_id, **kw)
    client.replicas = route
    return client

"""Socket transport: length-prefix framing + msgpack payloads.

The analogue of the reference's ``distkeras/networking.py`` (SURVEY.md §1
L1, §2.4): ``connect`` / ``send_msg`` / ``recv_msg`` with a fixed 8-byte
big-endian length header and a ``recvall`` loop, plus
``determine_host_address``.  Two deliberate departures from the
reference: payloads are msgpack maps of raw tensor bytes
(``host_ps.pack_params``'s template-implied raw encoding for
parameters, msgpack elsewhere), never pickle (no arbitrary-object
execution on receive), and Nagle is disabled on both ends (the PS
exchange is latency-bound request/response traffic).
"""

from __future__ import annotations

import os
import socket
import struct
from typing import Any

_HEADER = struct.Struct(">Q")


def _max_msg_bytes() -> int:
    """Sanity bound for the length header: a corrupt/garbage header
    must be rejected BEFORE ``recvall`` tries to allocate it.  Default
    1 GB (a ResNet-scale PS payload is ~45 MB; anything near a
    gigabyte is a desynced stream, not a parameter tree); override
    with ``DKT_MAX_MSG_BYTES`` for genuinely larger models."""
    return int(os.environ.get("DKT_MAX_MSG_BYTES", str(1 << 30)))


MAX_MSG_BYTES = _max_msg_bytes()


def determine_host_address() -> str:
    """Best-effort routable address of this host (the reference used the
    same trick: open a UDP socket to a public address and read the local
    endpoint; no traffic is sent)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def connect(host: str, port: int, timeout: float | None = None
            ) -> socket.socket:
    """``timeout`` bounds connection ESTABLISHMENT only.  It is cleared
    once connected: ``create_connection`` leaves the timeout armed on
    the socket, so a pull slower than the connect timeout (big model,
    busy PS) would raise ``socket.timeout`` MID-frame — desyncing the
    length-prefix stream for every later message on the connection."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def frame(*parts: bytes) -> bytes:
    """One wire frame: 8-byte big-endian length header + body (exposed
    so ``parallel.faults`` can truncate a real frame mid-wire)."""
    total = sum(len(p) for p in parts)
    return _HEADER.pack(total) + b"".join(parts)


def send_msg(sock: socket.socket, *parts: bytes) -> None:
    """Send one framed message made of ``parts`` (concatenated headers
    let a request carry a command byte + payload without copies)."""
    sock.sendall(frame(*parts))


# sendmsg gathers at most IOV_MAX buffers per call; 64 is far below
# every platform's limit and keeps the partial-send bookkeeping short
_IOV_MAX = 64


def _as_byte_view(part) -> memoryview:
    mv = part if isinstance(part, memoryview) else memoryview(part)
    if mv.format != "B" or not mv.contiguous:
        mv = mv.cast("B")
    return mv


def send_msg_gather(sock: socket.socket, *parts) -> int:
    """Zero-copy scatter-gather variant of ``send_msg``: ``parts`` may
    be ``bytes`` or ``memoryview``s (e.g. views of already-contiguous
    parameter leaves) and are framed as ONE message but written via
    ``socket.sendmsg`` — no ``tobytes()`` materialization and no
    ``b"".join`` concatenation copy (the two host copies ``pack_params``
    pays on the single-mutex PS wire, PERF.md §12/§25).  Returns the
    body byte count (header excluded) for wire accounting."""
    bufs = [_as_byte_view(p) for p in parts]
    total = sum(b.nbytes for b in bufs)
    bufs.insert(0, memoryview(_HEADER.pack(total)))
    i = 0
    while i < len(bufs):
        sent = sock.sendmsg(bufs[i:i + _IOV_MAX])
        while i < len(bufs) and sent >= bufs[i].nbytes:
            sent -= bufs[i].nbytes
            i += 1
        if sent:  # partial write inside buffer i: resume mid-buffer
            bufs[i] = bufs[i][sent:]
    return total


def recv_msg_into(sock: socket.socket) -> memoryview:
    """Receive one framed message into a single preallocated buffer
    (``recv_into`` — no chunk-list ``b"".join`` copy) and return a
    read-only memoryview over it.  ``numpy.frombuffer`` accepts the
    view directly, so a parameter payload is sliced into leaf arrays
    with zero further copies."""
    head = bytearray(_HEADER.size)
    _recv_into_all(sock, memoryview(head))
    (length,) = _HEADER.unpack(head)
    if length > MAX_MSG_BYTES:
        raise ValueError(
            f"message length {length} exceeds sanity bound "
            f"{MAX_MSG_BYTES} (DKT_MAX_MSG_BYTES)")
    body = bytearray(length)
    _recv_into_all(sock, memoryview(body))
    return memoryview(body).toreadonly()


def _recv_into_all(sock: socket.socket, mv: memoryview) -> None:
    off, n = 0, mv.nbytes
    while off < n:
        got = sock.recv_into(mv[off:], min(n - off, 1 << 20))
        if not got:
            raise ConnectionError("peer closed mid-message")
        off += got


def _recvall(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def pack_obj(obj: Any) -> bytes:
    """Self-describing msgpack encoding of a python object (dicts,
    lists, scalars, numpy arrays — flax's msgpack extension).  The
    serving-gateway wire uses this for request/result/health payloads,
    which have no pre-shared template the raw ``pack_params`` encoding
    could lean on.  Never pickle: nothing executable crosses the
    wire."""
    from flax import serialization as flax_serialization

    return flax_serialization.msgpack_serialize(obj)


def unpack_obj(data: bytes | memoryview) -> Any:
    """Inverse of ``pack_obj`` (template-free)."""
    from flax import serialization as flax_serialization

    return flax_serialization.msgpack_restore(bytes(data))


def recv_msg(sock: socket.socket) -> bytes:
    (length,) = _HEADER.unpack(_recvall(sock, _HEADER.size))
    if length > MAX_MSG_BYTES:
        # reject BEFORE allocating: a garbage header (desynced stream,
        # hostile peer) must not drive a multi-terabyte recv loop
        raise ValueError(
            f"message length {length} exceeds sanity bound "
            f"{MAX_MSG_BYTES} (DKT_MAX_MSG_BYTES)")
    return _recvall(sock, length)


# -- wire-op registry (ISSUE 9) ----------------------------------------
#
# Every single-byte command that crosses a framed socket is registered
# here, per protocol scope, instead of living as scattered literals in
# the dispatch/client code.  ``analysis/surfaces.py`` cross-checks the
# literals in the wire modules against this table, so an op byte cannot
# be added (or repurposed) without updating the registry — and the
# registry itself rejects the two real collision hazards: two meanings
# for one byte within a scope, and any scope reusing the trace-header
# magic (the PS and replica servers peek one byte to tell a traced
# frame from a bare one, so the magic must be globally unambiguous).


class WireOpCollision(ValueError):
    """A wire-op byte was registered twice with different meanings."""


class WireOps:
    """Per-scope registry of single-byte wire commands.

    Scopes are independent protocols (``"ps"`` and ``"replica"`` both
    use ``b"s"`` for stop — different servers, never ambiguous); the
    ``"frame"`` scope holds bytes that may prefix ANY frame (the trace
    magic) and therefore must not collide with any other scope."""

    def __init__(self) -> None:
        self._ops: dict[str, dict[bytes, str]] = {}

    def register(self, scope: str, op: bytes, name: str) -> bytes:
        if len(op) != 1:
            raise ValueError(f"wire op must be one byte, got {op!r}")
        table = self._ops.setdefault(scope, {})
        if table.get(op, name) != name:
            raise WireOpCollision(
                f"{scope}:{op!r} already registered as "
                f"{table[op]!r}, refusing {name!r}")
        for other, tab in self._ops.items():
            if other == scope:
                continue
            if (scope == "frame" or other == "frame") and op in tab:
                raise WireOpCollision(
                    f"{op!r} ({name!r} in {scope!r}) collides with "
                    f"frame-level byte {tab[op]!r} in {other!r}")
        table[op] = name
        return op

    def ops(self, scope: str) -> dict[bytes, str]:
        """The registered ``op byte -> name`` table for one scope."""
        return dict(self._ops.get(scope, {}))

    def scopes(self) -> tuple[str, ...]:
        return tuple(sorted(self._ops))


WIRE_OPS = WireOps()

# frame-level: may prefix any protocol's frames (see trace header below)
WIRE_OPS.register("frame", b"t", "trace_header")
# classic + sharded PS protocol (host_ps.PSServer._dispatch)
WIRE_OPS.register("ps", b"p", "pull")
WIRE_OPS.register("ps", b"c", "commit")
WIRE_OPS.register("ps", b"P", "pull_since")
WIRE_OPS.register("ps", b"C", "commit_shard")
WIRE_OPS.register("ps", b"d", "done")
WIRE_OPS.register("ps", b"s", "stop")
WIRE_OPS.register("ps", b"E", "epoch")
WIRE_OPS.register("ps", b"V", "center_obj")
# PS replication protocol (replicated_ps: primary -> standby log
# shipping plus the standby's replies; requests a/h/?/b, replies k/f/g)
WIRE_OPS.register("repl", b"a", "append")
WIRE_OPS.register("repl", b"h", "heartbeat")
WIRE_OPS.register("repl", b"?", "status")
WIRE_OPS.register("repl", b"b", "bootstrap")
WIRE_OPS.register("repl", b"k", "ack")
WIRE_OPS.register("repl", b"f", "fenced")
WIRE_OPS.register("repl", b"g", "gap")
# elastic PS protocol (elastic_ps.ElasticPSServer._serve): versioned
# shard-map routing plus the migration snapshot/tail-log stream
WIRE_OPS.register("elastic", b"m", "fetch_map")
WIRE_OPS.register("elastic", b"g", "pull_versioned")
WIRE_OPS.register("elastic", b"c", "commit_shard")
WIRE_OPS.register("elastic", b"B", "migrate_bootstrap")
WIRE_OPS.register("elastic", b"A", "migrate_append")
WIRE_OPS.register("elastic", b"F", "migrate_finalize")
WIRE_OPS.register("elastic", b"d", "done")
WIRE_OPS.register("elastic", b"s", "stop")
# serving-replica protocol (gateway.ReplicaServer._dispatch)
WIRE_OPS.register("replica", b"g", "generate")
WIRE_OPS.register("replica", b"h", "health")
WIRE_OPS.register("replica", b"w", "swap_weights")
WIRE_OPS.register("replica", b"v", "variables")
WIRE_OPS.register("replica", b"q", "quiesce")
WIRE_OPS.register("replica", b"s", "stop")
# disaggregated prefill/decode handoff (ISSUE 19): kv_probe asks how
# many leading prompt blocks a replica's prefix store already holds,
# kv_export streams them out, kv_import installs a shipped block set
WIRE_OPS.register("replica", b"y", "kv_probe")
WIRE_OPS.register("replica", b"x", "kv_export")
WIRE_OPS.register("replica", b"k", "kv_import")
# KV page-block interchange payload (serving.pack_kv_blocks /
# unpack_kv_blocks): ONE gather-sent frame = the block-set op byte, an
# 8-byte BE meta length, the msgpack meta (prompt, per-leaf shape/
# dtype templates), then every block's raw leaf bytes back to back —
# zero-copy on the send side (page memoryviews ride ``sendmsg``)
WIRE_OPS.register("kv", b"K", "page_blocks")
# hierarchical aggregation tier (hier_ps.HierPSServer._dispatch): one
# pre-reduced group window — seq + per-worker staleness vector + the
# folded delta — answered with the root's new center (ISSUE 20)
WIRE_OPS.register("hier", b"u", "upstream_commit")


# -- trace-context wire header (ISSUE 6) -------------------------------
#
# When tracing is enabled, PS requests prepend a 17-byte header to the
# frame body: ``b"t" + trace_id(8B BE) + span_id(8B BE)``.  ``b"t"`` is
# not a PS command byte, so the server peeks one byte to tell a traced
# request from a bare one — and when tracing is off the header is the
# EMPTY byte string, adding zero wire bytes (the PERF.md §24 criterion).

_TRACE_HEADER = struct.Struct(">QQ")
TRACE_HEADER_LEN = 1 + _TRACE_HEADER.size  # magic + two 64-bit ids


def trace_header() -> bytes:
    """The 17-byte trace-context header for the CURRENT thread's
    innermost live span, or ``b""`` (zero bytes) when no span is open
    — i.e. always when telemetry is disabled."""
    from distkeras_tpu import telemetry
    ctx = telemetry.current_trace()
    if ctx is None:
        return b""
    return b"t" + _TRACE_HEADER.pack(ctx[0], ctx[1])


def split_trace_header(body: memoryview | bytes
                       ) -> tuple[tuple[int, int] | None, Any]:
    """Strip a leading trace-context header off a received frame body:
    returns ``((trace_id, span_id), rest)`` when present, ``(None,
    body)`` otherwise — the caller dispatches on ``rest`` exactly as it
    would have on an untraced body."""
    if len(body) >= TRACE_HEADER_LEN and bytes(body[:1]) == b"t":
        trace_id, span_id = _TRACE_HEADER.unpack(
            bytes(body[1:TRACE_HEADER_LEN]))
        return (trace_id, span_id), body[TRACE_HEADER_LEN:]
    return None, body

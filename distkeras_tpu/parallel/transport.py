"""Socket transport: length-prefix framing + msgpack payloads.

The analogue of the reference's ``distkeras/networking.py`` (SURVEY.md §1
L1, §2.4): ``connect`` / ``send_msg`` / ``recv_msg`` with a fixed 8-byte
big-endian length header and a ``recvall`` loop, plus
``determine_host_address``.  Two deliberate departures from the
reference: payloads are msgpack maps of raw tensor bytes
(``host_ps.pack_params``'s template-implied raw encoding for
parameters, msgpack elsewhere), never pickle (no arbitrary-object
execution on receive), and Nagle is disabled on both ends (the PS
exchange is latency-bound request/response traffic).
"""

from __future__ import annotations

import socket
import struct

_HEADER = struct.Struct(">Q")
MAX_MSG_BYTES = 1 << 40  # sanity bound for the length header


def determine_host_address() -> str:
    """Best-effort routable address of this host (the reference used the
    same trick: open a UDP socket to a public address and read the local
    endpoint; no traffic is sent)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def connect(host: str, port: int, timeout: float | None = None
            ) -> socket.socket:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def send_msg(sock: socket.socket, *parts: bytes) -> None:
    """Send one framed message made of ``parts`` (concatenated headers
    let a request carry a command byte + payload without copies)."""
    total = sum(len(p) for p in parts)
    sock.sendall(_HEADER.pack(total) + b"".join(parts))


def _recvall(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> bytes:
    (length,) = _HEADER.unpack(_recvall(sock, _HEADER.size))
    if length > MAX_MSG_BYTES:
        raise ValueError(f"message length {length} exceeds sanity bound")
    return _recvall(sock, length)

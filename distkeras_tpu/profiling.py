"""Profiling / benchmarking utilities (SURVEY.md §5 "honest
observability": the reference records only wall-clock ``training_time``;
the rebuild ships peak-FLOPs and peak-bandwidth tables, MFU accounting,
safe device-sync timing, and a ``jax.profiler`` trace hook that anchors
the device timeline to the host span clock).

Shared by ``bench.py``, ``distkeras_tpu.attrib`` and the
``scripts/perf_*.py`` experiments so the constants and the timing
workaround live in exactly one place.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import time
from typing import Iterator

import jax
import jax.numpy as jnp

#: bf16 peak FLOP/s per chip by device kind (public spec sheets).  The
#: ``"cpu"`` row is a NOMINAL placeholder for CI runs off-TPU — it is
#: deliberately reported as ``known=False`` by :func:`peak_flops` so an
#: MFU computed against it carries an explicit ``peak_known: false``
#: flag instead of looking authoritative.
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "cpu": 1e12,  # nominal, for CI runs off-TPU (known=False)
}

#: HBM bandwidth, bytes/s per chip (public spec sheets) — the
#: denominator of the roofline's communication term.  On the CPU
#: backend collectives are memcpys through host memory; the nominal row
#: keeps the roofline computable there (flagged ``known=False``).
PEAK_BYTES_PER_SEC = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 820e9,
    "TPU v5e": 820e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
    "cpu": 50e9,  # nominal host-memory figure (known=False)
}

#: Analytic forward FLOPs (2 x MACs) per image for ResNet-50 @ 224px
#: (torchvision: 4.09 GMACs).  Training step ~= 3x forward.  See PERF.md
#: §1 for why MFU uses this rather than XLA's executed-FLOPs counter.
RESNET50_FWD_GFLOPS_224 = 8.18

#: Device kinds whose table rows are nominal placeholders, not spec
#: sheets.  A lookup that lands here still returns the value (so CI
#: rooflines stay computable) but with ``known=False`` — callers must
#: surface that flag (``peak_known`` in bench records) rather than let
#: a guessed CPU peak masquerade as measured hardware.
_NOMINAL_KINDS = frozenset({"cpu"})


def _peak_lookup(table: dict, device) -> tuple[float, bool]:
    kind = getattr(device, "device_kind", "cpu")
    for key, val in table.items():
        if kind.lower().startswith(key.lower()):
            return val, key not in _NOMINAL_KINDS
    return float("nan"), False


def peak_flops(device) -> tuple[float, bool]:
    """(bf16 peak FLOP/s, known?) for ``device``.

    Spec-sheet kinds return ``known=True``.  The CPU backend returns
    its NOMINAL table value with ``known=False`` — usable for relative
    CI gating, but callers must record the flag (``peak_known``)
    instead of presenting the MFU as authoritative.  Unknown kinds
    return ``(nan, False)``; callers must omit or null their MFU
    figures rather than fabricate a peak (ADVICE.md r1).
    """
    return _peak_lookup(PEAK_FLOPS, device)


def peak_bandwidth(device) -> tuple[float, bool]:
    """(peak bytes/s, known?) for ``device`` — same semantics as
    :func:`peak_flops` (nominal CPU row, ``known=False``)."""
    return _peak_lookup(PEAK_BYTES_PER_SEC, device)


def resnet50_model_flops(batch: int, image: int = 224,
                         train: bool = True) -> float:
    """Analytic model FLOPs for one ResNet-50 step."""
    scale = (image / 224) ** 2
    return (RESNET50_FWD_GFLOPS_224 * 1e9 * scale * batch
            * (3 if train else 1))


def bench_device_config() -> dict:
    """One place for ``bench.py``'s device/shape assumptions (ISSUE 16
    satellite — they were hardcoded inline, so the mesh arm would have
    had to duplicate them).  ResNet-50 at the published shape on TPU;
    a CPU run shrinks to a CI-sized problem rather than lying with an
    un-runnable one.  ``n_devices`` is what ``--mode auto`` keys off.
    """
    devices = jax.devices()
    device = devices[0]
    on_tpu = device.platform != "cpu"
    return {
        "devices": devices,
        "device": device,
        "n_devices": len(devices),
        "on_tpu": on_tpu,
        "batch": 256 if on_tpu else 4,
        "image": 224 if on_tpu else 64,
        "num_classes": 1000 if on_tpu else 10,
    }


def train_mfu(images_per_sec: float, image: int, device,
              n_chips: int = 1) -> float | None:
    """Analytic-model-FLOPs MFU, honest across chip counts: total
    images/sec x FLOPs per training image, over ``n_chips`` x peak.
    Returns ``None`` when the device kind has no peak AT ALL (not even
    a nominal row); a nominal-peak figure is returned but callers must
    pair it with the ``known`` flag from :func:`peak_flops`
    (``peak_known`` in bench records) so it cannot masquerade as a
    measured-hardware number.  Both ``bench.py`` arms and the flagship
    script use THIS accounting, so a mesh number and a single-chip
    number are directly comparable.
    """
    peak, _known = peak_flops(device)
    if peak != peak:  # NaN: no table row, nothing honest to divide by
        return None
    return (resnet50_model_flops(1, image) * images_per_sec
            / (peak * n_chips))


def host_sync(out) -> float:
    """Force full device execution by fetching one scalar to the host.

    On the tunneled TPU platform ``jax.block_until_ready`` can return
    before execution finishes, but a host transfer cannot (it depends on
    the whole computation chain).  Returns the fetched scalar.
    """
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.real(leaf.reshape(-1)[0]).astype(jnp.float32))


def time_step_chain(step_fn, state, batch, n: int = 20,
                    warmup: int = 2) -> tuple[float, float]:
    """Time ``step_fn(state, batch) -> (state, metrics)`` over a chain.

    Threads the (possibly donated) state through the chain and syncs on
    the final metrics, so it is safe for ``jax.jit(..., donate_argnums=0)``
    functions.  Returns ``(seconds_per_call, synced_metric_scalar)`` —
    the scalar is the first metrics leaf, useful as a finite-ness health
    check.  Divide seconds by the window length yourself when timing
    scanned windows.
    """
    for _ in range(max(warmup, 1)):
        state, metrics = step_fn(state, batch)
    host_sync(metrics)
    t0 = time.perf_counter()
    for _ in range(n):
        state, metrics = step_fn(state, batch)
    value = host_sync(metrics)
    return (time.perf_counter() - t0) / n, value


def telemetry_overhead(n: int = 200_000) -> dict:
    """Measured per-call cost (ns) of the telemetry hot-path
    primitives, disabled vs enabled — the number PERF.md §24 quotes
    and ``scripts/obs_report.py`` re-measures.  Restores the global
    telemetry state it found.

    The disabled arm is what every instrumented call site pays when
    telemetry is off (the tier-1 / perf-row fast path): a registry
    lookup returning the shared no-op metric, and the shared no-op
    span.  The enabled arm adds the real lock + dict work.
    """
    from distkeras_tpu import telemetry

    def per_call_ns(fn) -> float:
        fn()  # warm any lazy allocation out of the timed loop
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n * 1e9

    def inc_op():
        telemetry.metrics().counter("overhead_probe").inc()

    def span_op():
        with telemetry.span("overhead_probe"):
            pass

    prior = telemetry.get() if telemetry.enabled() else None
    out = {}
    try:
        telemetry.disable()
        out["disabled_counter_inc_ns"] = round(per_call_ns(inc_op), 1)
        out["disabled_span_ns"] = round(per_call_ns(span_op), 1)
        telemetry.enable()
        out["enabled_counter_inc_ns"] = round(per_call_ns(inc_op), 1)
        out["enabled_span_ns"] = round(per_call_ns(span_op), 1)
    finally:
        if prior is not None:
            telemetry.enable(telemetry=prior)
        else:
            telemetry.disable()
    return out


#: Filename of the wall-clock anchor :func:`profiler_trace` drops next
#: to a device capture; ``telemetry.load_device_trace`` reads it to pin
#: the trace's relative timestamps onto the host span timeline.
WALL_ANCHOR_FILE = "wall_anchor.json"


@contextlib.contextmanager
def profiler_trace(log_dir: str | None) -> Iterator[None]:
    """``jax.profiler`` trace hook: no-op when ``log_dir`` is None, so
    trainers can accept an optional ``profile_dir`` flag without
    branching at every call site.

    When active, writes ``wall_anchor.json`` (the wall clock at
    ``start_trace``) into ``log_dir`` FIRST: XLA's ``trace.json.gz``
    timestamps are microseconds RELATIVE to the capture start, and the
    anchor is what lets ``telemetry.load_device_trace`` /
    ``merge_traces`` shift them onto the host tracer's monotonic
    timeline for one unified Perfetto file.
    """
    if log_dir is None:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    anchor = {"wall_s": time.time()}
    with open(os.path.join(log_dir, WALL_ANCHOR_FILE), "w") as f:
        json.dump(anchor, f)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def find_device_traces(log_dir: str) -> list[str]:
    """Chrome-format device traces under a :func:`profiler_trace` log
    dir (``plugins/profile/<run>/<host>.trace.json.gz``), newest first.
    Empty when the profiler produced nothing — callers skip cleanly.
    """
    pattern = os.path.join(log_dir, "**", "*.trace.json.gz")
    hits = glob.glob(pattern, recursive=True)
    hits += glob.glob(os.path.join(log_dir, "**", "*.trace.json"),
                      recursive=True)
    return sorted(set(hits), key=os.path.getmtime, reverse=True)

"""Compute ops: losses, eval metrics, and (Pallas) kernels.

The reference has no op layer of its own — Keras/Theano supplied it
(SURVEY.md §1 "no ops/kernel layer").  The rebuild's op layer is jittable
functions over logits/labels, fused by XLA; hand-written Pallas kernels
live in ``distkeras_tpu.ops.pallas_kernels`` for the cases XLA doesn't
fuse well.
"""

from distkeras_tpu.ops.losses import LOSSES, resolve_loss  # noqa: F401
from distkeras_tpu.ops.metrics import (  # noqa: F401
    accuracy,
    binary_accuracy,
    perplexity,
    top_k_accuracy,
)

# Pallas-backed ops are lazy (module __getattr__), matching the
# non-re-exported pallas_kernels/fused_block precedent: importing the
# package must not pull jax.experimental.pallas + Mosaic machinery in
# for users who never touch a kernel path.
_LAZY = {"flash_attention", "flash_attn_fn"}


def __getattr__(name):
    if name in _LAZY:
        # The submodule is named `attention` precisely so none of its
        # exported functions collide with a submodule name — the
        # package attr binding stays stable no matter what was
        # imported first.
        from distkeras_tpu.ops import attention as _attn

        for n in _LAZY:
            globals()[n] = getattr(_attn, n)
        return globals()[name]
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

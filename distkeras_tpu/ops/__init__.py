"""Compute ops: losses, eval metrics, and (Pallas) kernels.

The reference has no op layer of its own — Keras/Theano supplied it
(SURVEY.md §1 "no ops/kernel layer").  The rebuild's op layer is jittable
functions over logits/labels, fused by XLA; hand-written Pallas kernels
live in ``distkeras_tpu.ops.pallas_kernels`` for the cases XLA doesn't
fuse well.
"""

from distkeras_tpu.ops.losses import LOSSES, resolve_loss  # noqa: F401
from distkeras_tpu.ops.metrics import (  # noqa: F401
    accuracy,
    binary_accuracy,
    top_k_accuracy,
)

"""Block-granular fused Pallas kernels for the ResNet bottleneck.

PERF.md §4's post-mortem on the standalone GroupNorm kernel: on TPU you
beat the fusion *boundary*, not the op — a custom call that replaces one
op severs XLA's conv↔norm↔relu fusion clusters on both sides and loses.
These kernels therefore own a whole block region, so there is nothing
left at the boundary to sever:

``fused_conv1x1_gn``
    ``y = [relu](gn(x @ w))`` — a 1x1 convolution (spatially pointwise,
    so a plain matmul over ``[H*W, C]``) with GroupNorm statistics,
    affine, and optional ReLU computed while the sample's activations
    are resident in VMEM.  One HBM read of ``x``, one HBM write of
    ``y`` — versus conv-write + stats-read + normalize-read/write when
    the norm is a separate XLA cluster.  Covers the bottleneck's first
    1x1 conv and the downsample projection (``relu=False``).

``fused_bottleneck_tail``
    ``out = relu(gn3(relu(gn2(y2)) @ w3) + residual)`` — absorbs the
    3x3 conv's GroupNorm, the second 1x1 conv, its GroupNorm, the
    residual add, and the final ReLU in one pass: reads ``y2`` (the raw
    3x3-conv output) and ``residual`` once, writes ``out`` once.

Backward passes are hand-written kernels (``jax.custom_vjp``) that
RECOMPUTE the forward intermediates from the saved inputs inside VMEM
instead of materializing them to HBM: in this bandwidth-bound regime
(PERF.md §3: ResNet-50 on v5e sits at an arithmetic intensity well
below the chip's peak ratio) an extra MXU matmul is cheaper than an
extra HBM traversal.

Per-group reductions use the ``[C, G]`` 0/1 mask-matmul trick from
``pallas_kernels.py`` (lane-dimension reshapes lower poorly in Mosaic).
Grid is one sample per step — GroupNorm statistics are per-sample, so
the sample axis is embarrassingly parallel and Pallas double-buffers
the HBM↔VMEM streams across grid steps.

No counterpart in the reference: it has no kernel layer (SURVEY.md §1
— Keras/Theano supplied compute).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distkeras_tpu.ops.pallas_kernels import _CompilerParams, _group_mask

# Whole-sample blocks at ResNet-50 stage 1 ([3136, 256] f32
# intermediates, several live at once in the tail backward) need more
# than the default 16 MB scoped-VMEM budget; v5e has 128 MB.
_VMEM_LIMIT = _CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)


def _gn_stats(y, mask, count, eps):
    """Per-group mean / inverse-stddev of ``y`` [HW, C] via the [C, G]
    group mask; returns channel-broadcast ``(mean_c, inv_c)`` [1, C]."""
    s1 = jnp.sum(y, axis=0, keepdims=True)          # [1, C]
    s2 = jnp.sum(y * y, axis=0, keepdims=True)      # [1, C]
    g1 = jnp.dot(s1, mask, preferred_element_type=jnp.float32) / count
    g2 = jnp.dot(s2, mask, preferred_element_type=jnp.float32) / count
    var = jnp.maximum(g2 - g1 * g1, 0.0)
    inv = jax.lax.rsqrt(var + eps)                  # [1, G]
    mean_c = jnp.dot(g1, mask.T, preferred_element_type=jnp.float32)
    inv_c = jnp.dot(inv, mask.T, preferred_element_type=jnp.float32)
    return mean_c, inv_c


def _gn_bwd(dz, xhat, gamma, mask, count, inv_c):
    """Standard GroupNorm VJP: cotangent w.r.t. the raw (pre-norm)
    tensor, plus per-channel dgamma/dbeta rows.  All [HW, C] f32."""
    dgamma = jnp.sum(dz * xhat, axis=0, keepdims=True)   # [1, C]
    dbeta = jnp.sum(dz, axis=0, keepdims=True)           # [1, C]
    dzg = dz * gamma                                      # [HW, C]
    t1 = jnp.dot(jnp.sum(dzg, axis=0, keepdims=True), mask,
                 preferred_element_type=jnp.float32)      # [1, G]
    t2 = jnp.dot(jnp.sum(dzg * xhat, axis=0, keepdims=True), mask,
                 preferred_element_type=jnp.float32)      # [1, G]
    t1_c = jnp.dot(t1, mask.T, preferred_element_type=jnp.float32)
    t2_c = jnp.dot(t2, mask.T, preferred_element_type=jnp.float32)
    dy_raw = inv_c * (dzg - t1_c / count - xhat * (t2_c / count))
    return dy_raw, dgamma, dbeta


# ---------------------------------------------------------------------------
# Kernel A: y = [relu](gn(x @ w))
# ---------------------------------------------------------------------------


def _conv_gn_fwd_kernel(x_ref, w_ref, gamma_ref, beta_ref, mask_ref,
                        y_ref, *, eps, relu, count):
    x = x_ref[0]                                          # [HW, Cin] bf16
    y = jnp.dot(x, w_ref[:], preferred_element_type=jnp.float32)
    mean_c, inv_c = _gn_stats(y, mask_ref[:], count, eps)
    out = (y - mean_c) * inv_c * gamma_ref[:] + beta_ref[:]
    if relu:
        out = jnp.maximum(out, 0.0)
    y_ref[0] = out.astype(y_ref.dtype)


def _conv_gn_bwd_kernel(x_ref, w_ref, gamma_ref, beta_ref, mask_ref,
                        dy_ref, dx_ref, dw_ref, dgamma_ref, dbeta_ref,
                        *, eps, relu, count):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        dgamma_ref[:] = jnp.zeros_like(dgamma_ref)
        dbeta_ref[:] = jnp.zeros_like(dbeta_ref)

    x = x_ref[0]                                          # [HW, Cin]
    w = w_ref[:]
    mask = mask_ref[:]
    gamma = gamma_ref[:]
    dz = dy_ref[0].astype(jnp.float32)                    # [HW, Cout]
    # recompute the forward in VMEM (cheaper than an HBM round-trip)
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    mean_c, inv_c = _gn_stats(y, mask, count, eps)
    xhat = (y - mean_c) * inv_c
    if relu:
        z = xhat * gamma + beta_ref[:]
        dz = jnp.where(z > 0, dz, 0.0)
    dy_raw, dgamma, dbeta = _gn_bwd(dz, xhat, gamma, mask, count, inv_c)
    dgamma_ref[:] += dgamma
    dbeta_ref[:] += dbeta
    dy_b = dy_raw.astype(x.dtype)
    dx_ref[0] = jnp.dot(dy_b, w.T,
                        preferred_element_type=jnp.float32
                        ).astype(dx_ref.dtype)
    dw_ref[:] += jnp.dot(x.T, dy_b,
                         preferred_element_type=jnp.float32)


def _row_spec(c):
    return pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM)


def _mat_spec(r, c):
    return pl.BlockSpec((r, c), lambda i: (0, 0), memory_space=pltpu.VMEM)


def _sample_spec(hw, c):
    return pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _conv_gn(x3, w, gamma, beta, groups, eps, relu, interpret):
    b, hw, cin = x3.shape
    cout = w.shape[1]
    mask = jnp.asarray(_group_mask(cout, groups))
    kernel = functools.partial(_conv_gn_fwd_kernel, eps=eps, relu=relu,
                               count=float(hw * (cout // groups)))
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[_sample_spec(hw, cin), _mat_spec(cin, cout),
                  _row_spec(cout), _row_spec(cout),
                  _mat_spec(cout, groups)],
        out_specs=_sample_spec(hw, cout),
        out_shape=jax.ShapeDtypeStruct((b, hw, cout), x3.dtype),
        compiler_params=None if interpret else _VMEM_LIMIT,
        interpret=interpret,
    )(x3, w, gamma, beta, mask)


def _conv_gn_fwd(x3, w, gamma, beta, groups, eps, relu, interpret):
    y = _conv_gn(x3, w, gamma, beta, groups, eps, relu, interpret)
    return y, (x3, w, gamma, beta)


def _conv_gn_bwd(groups, eps, relu, interpret, res, dy):
    x3, w, gamma, beta = res
    b, hw, cin = x3.shape
    cout = w.shape[1]
    mask = jnp.asarray(_group_mask(cout, groups))
    kernel = functools.partial(_conv_gn_bwd_kernel, eps=eps, relu=relu,
                               count=float(hw * (cout // groups)))
    dx, dw, dgamma, dbeta = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[_sample_spec(hw, cin), _mat_spec(cin, cout),
                  _row_spec(cout), _row_spec(cout),
                  _mat_spec(cout, groups), _sample_spec(hw, cout)],
        out_specs=[_sample_spec(hw, cin), _mat_spec(cin, cout),
                   _row_spec(cout), _row_spec(cout)],
        out_shape=[
            jax.ShapeDtypeStruct((b, hw, cin), x3.dtype),
            jax.ShapeDtypeStruct((cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        compiler_params=None if interpret else _VMEM_LIMIT,
        interpret=interpret,
    )(x3, w, gamma, beta, mask, dy)
    return dx, dw.astype(w.dtype), dgamma.astype(gamma.dtype), \
        dbeta.astype(beta.dtype)


_conv_gn.defvjp(_conv_gn_fwd, _conv_gn_bwd)


def fused_conv1x1_gn(x, w, gamma, beta, *, groups, eps=1e-6, relu=True,
                     interpret=None):
    """1x1-conv + GroupNorm + optional ReLU in one HBM pass.

    ``x``: [N, ..., Cin] (channels last; spatial dims flattened
    internally — a 1x1 conv is pointwise).  ``w``: [Cin, Cout].
    ``gamma``/``beta``: [Cout].  Differentiable in x/w/gamma/beta.
    ``interpret=None`` auto-enables the Pallas interpreter off-TPU.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    shape = x.shape
    cin = shape[-1]
    b = shape[0]
    hw = int(np.prod(shape[1:-1])) if len(shape) > 2 else 1
    x3 = x.reshape(b, hw, cin)
    y3 = _conv_gn(x3, w, gamma.reshape(1, -1).astype(jnp.float32),
                  beta.reshape(1, -1).astype(jnp.float32),
                  int(groups), float(eps), bool(relu), bool(interpret))
    return y3.reshape(shape[:-1] + (w.shape[1],))


# ---------------------------------------------------------------------------
# Kernel B: out = relu(gn3(relu(gn2(y2)) @ w3) + residual)
# ---------------------------------------------------------------------------


def _tail_fwd_kernel(y2_ref, w_ref, g2_ref, b2_ref, g3_ref, b3_ref,
                     res_ref, mask2_ref, mask3_ref, out_ref, *,
                     eps, count2, count3):
    y2 = y2_ref[0].astype(jnp.float32)                    # [HW, Cm]
    mean2, inv2 = _gn_stats(y2, mask2_ref[:], count2, eps)
    h = jnp.maximum((y2 - mean2) * inv2 * g2_ref[:] + b2_ref[:], 0.0)
    y3 = jnp.dot(h.astype(y2_ref.dtype), w_ref[:],
                 preferred_element_type=jnp.float32)      # [HW, Cout]
    mean3, inv3 = _gn_stats(y3, mask3_ref[:], count3, eps)
    z = (y3 - mean3) * inv3 * g3_ref[:] + b3_ref[:]
    out = jnp.maximum(z + res_ref[0].astype(jnp.float32), 0.0)
    out_ref[0] = out.astype(out_ref.dtype)


def _tail_bwd_kernel(y2_ref, w_ref, g2_ref, b2_ref, g3_ref, b3_ref,
                     res_ref, mask2_ref, mask3_ref, dout_ref,
                     dy2_ref, dw_ref, dg2_ref, db2_ref, dg3_ref,
                     db3_ref, dres_ref, *, eps, count2, count3):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        dg2_ref[:] = jnp.zeros_like(dg2_ref)
        db2_ref[:] = jnp.zeros_like(db2_ref)
        dg3_ref[:] = jnp.zeros_like(dg3_ref)
        db3_ref[:] = jnp.zeros_like(db3_ref)

    w = w_ref[:]
    mask2, mask3 = mask2_ref[:], mask3_ref[:]
    g2, g3 = g2_ref[:], g3_ref[:]
    # recompute the forward chain in VMEM
    y2 = y2_ref[0].astype(jnp.float32)
    mean2, inv2 = _gn_stats(y2, mask2, count2, eps)
    xhat2 = (y2 - mean2) * inv2
    u = xhat2 * g2 + b2_ref[:]
    h = jnp.maximum(u, 0.0)
    hb = h.astype(y2_ref.dtype)
    y3 = jnp.dot(hb, w, preferred_element_type=jnp.float32)
    mean3, inv3 = _gn_stats(y3, mask3, count3, eps)
    xhat3 = (y3 - mean3) * inv3
    z = xhat3 * g3 + b3_ref[:] + res_ref[0].astype(jnp.float32)
    # backward
    dz = jnp.where(z > 0, dout_ref[0].astype(jnp.float32), 0.0)
    dres_ref[0] = dz.astype(dres_ref.dtype)
    dy3, dg3, db3 = _gn_bwd(dz, xhat3, g3, mask3, count3, inv3)
    dg3_ref[:] += dg3
    db3_ref[:] += db3
    dy3_b = dy3.astype(y2_ref.dtype)
    dw_ref[:] += jnp.dot(hb.T, dy3_b,
                         preferred_element_type=jnp.float32)
    dh = jnp.dot(dy3_b, w.T, preferred_element_type=jnp.float32)
    dh = jnp.where(u > 0, dh, 0.0)
    dy2, dg2, db2 = _gn_bwd(dh, xhat2, g2, mask2, count2, inv2)
    dg2_ref[:] += dg2
    db2_ref[:] += db2
    dy2_ref[0] = dy2.astype(dy2_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _tail(y2, w, g2, b2, g3, b3, res, groups2, groups3, eps, interpret):
    b, hw, cm = y2.shape
    cout = w.shape[1]
    mask2 = jnp.asarray(_group_mask(cm, groups2))
    mask3 = jnp.asarray(_group_mask(cout, groups3))
    kernel = functools.partial(
        _tail_fwd_kernel, eps=eps,
        count2=float(hw * (cm // groups2)),
        count3=float(hw * (cout // groups3)))
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[_sample_spec(hw, cm), _mat_spec(cm, cout),
                  _row_spec(cm), _row_spec(cm),
                  _row_spec(cout), _row_spec(cout),
                  _sample_spec(hw, cout),
                  _mat_spec(cm, groups2), _mat_spec(cout, groups3)],
        out_specs=_sample_spec(hw, cout),
        out_shape=jax.ShapeDtypeStruct((b, hw, cout), y2.dtype),
        compiler_params=None if interpret else _VMEM_LIMIT,
        interpret=interpret,
    )(y2, w, g2, b2, g3, b3, res, mask2, mask3)


def _tail_fwd(y2, w, g2, b2, g3, b3, res, groups2, groups3, eps,
              interpret):
    out = _tail(y2, w, g2, b2, g3, b3, res, groups2, groups3, eps,
                interpret)
    return out, (y2, w, g2, b2, g3, b3, res)


def _tail_bwd(groups2, groups3, eps, interpret, saved, dout):
    y2, w, g2, b2, g3, b3, res = saved
    b, hw, cm = y2.shape
    cout = w.shape[1]
    mask2 = jnp.asarray(_group_mask(cm, groups2))
    mask3 = jnp.asarray(_group_mask(cout, groups3))
    kernel = functools.partial(
        _tail_bwd_kernel, eps=eps,
        count2=float(hw * (cm // groups2)),
        count3=float(hw * (cout // groups3)))
    dy2, dw, dg2, db2, dg3, db3, dres = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[_sample_spec(hw, cm), _mat_spec(cm, cout),
                  _row_spec(cm), _row_spec(cm),
                  _row_spec(cout), _row_spec(cout),
                  _sample_spec(hw, cout),
                  _mat_spec(cm, groups2), _mat_spec(cout, groups3),
                  _sample_spec(hw, cout)],
        out_specs=[_sample_spec(hw, cm), _mat_spec(cm, cout),
                   _row_spec(cm), _row_spec(cm),
                   _row_spec(cout), _row_spec(cout),
                   _sample_spec(hw, cout)],
        out_shape=[
            jax.ShapeDtypeStruct((b, hw, cm), y2.dtype),
            jax.ShapeDtypeStruct((cm, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cm), jnp.float32),
            jax.ShapeDtypeStruct((1, cm), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((b, hw, cout), res.dtype),
        ],
        compiler_params=None if interpret else _VMEM_LIMIT,
        interpret=interpret,
    )(y2, w, g2, b2, g3, b3, res, mask2, mask3, dout)
    return dy2, dw.astype(w.dtype), dg2.astype(g2.dtype), \
        db2.astype(b2.dtype), dg3.astype(g3.dtype), \
        db3.astype(b3.dtype), dres


_tail.defvjp(_tail_fwd, _tail_bwd)


def fused_bottleneck_tail(y2, w, gamma2, beta2, gamma3, beta3,
                          residual, *, groups2, groups3, eps=1e-6,
                          interpret=None):
    """The bottleneck's tail — ``relu(gn3(relu(gn2(y2)) @ w) + res)`` —
    in one HBM pass.

    ``y2``: [N, ..., Cm] raw 3x3-conv output (pre-norm).  ``w``:
    [Cm, Cout].  ``residual``: [N, ..., Cout].  Differentiable in every
    tensor argument (including the residual).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    shape = y2.shape
    cm = shape[-1]
    b = shape[0]
    hw = int(np.prod(shape[1:-1])) if len(shape) > 2 else 1
    out3 = _tail(y2.reshape(b, hw, cm), w,
                 gamma2.reshape(1, -1).astype(jnp.float32),
                 beta2.reshape(1, -1).astype(jnp.float32),
                 gamma3.reshape(1, -1).astype(jnp.float32),
                 beta3.reshape(1, -1).astype(jnp.float32),
                 residual.reshape(b, hw, w.shape[1]),
                 int(groups2), int(groups3), float(eps),
                 bool(interpret))
    return out3.reshape(shape[:-1] + (w.shape[1],))


def conv1x1_gn_reference(x, w, gamma, beta, *, groups, eps=1e-6,
                         relu=True):
    """Pure-jnp oracle for ``fused_conv1x1_gn`` (bf16-faithful: matmul
    in the input dtype with f32 accumulation, norm math in f32)."""
    from distkeras_tpu.ops.pallas_kernels import group_norm_reference

    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    out = group_norm_reference(y, gamma, beta, groups=groups, eps=eps,
                               relu=relu)
    return out.astype(x.dtype)


def bottleneck_tail_reference(y2, w, gamma2, beta2, gamma3, beta3,
                              residual, *, groups2, groups3, eps=1e-6):
    """Pure-jnp oracle for ``fused_bottleneck_tail``."""
    from distkeras_tpu.ops.pallas_kernels import group_norm_reference

    h = group_norm_reference(y2.astype(jnp.float32), gamma2, beta2,
                             groups=groups2, eps=eps, relu=True)
    y3 = jnp.dot(h.astype(y2.dtype), w,
                 preferred_element_type=jnp.float32)
    z = group_norm_reference(y3, gamma3, beta3, groups=groups3,
                             eps=eps, relu=False)
    out = jnp.maximum(z + residual.astype(jnp.float32), 0.0)
    return out.astype(y2.dtype)

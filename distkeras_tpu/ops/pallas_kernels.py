"""Hand-written Pallas TPU kernels for the hot ops XLA doesn't fuse well.

``fused_group_norm`` — GroupNorm(+ReLU) in ONE pass over HBM.  PERF.md §3
measured GroupNorm at 26% of the flagship ResNet-50 step: XLA lowers
flax's GroupNorm into separate stats/normalize passes over activations
that are far too large for cache (e.g. [256, 112, 112, 64] ≈ 410 MB
bf16), so the tensor crosses HBM several times.  This kernel keeps each
image's activations resident in VMEM: one HBM read, one HBM write, with
the affine transform and optional ReLU fused in.

Layout strategy: activations are processed as ``[HW, C]`` blocks (one
image per grid step).  Per-group statistics use a ``[C, G]`` 0/1
group-mask matrix, so "sum within each group's channels" is a tiny
matmul — no lane-dimension reshapes, which Mosaic lowers poorly; the
spatial reduction is a native sublane reduction.  The backward pass is a
second single-pass kernel (standard GroupNorm VJP algebra, recomputing
x-hat from saved per-group stats), wired via ``jax.custom_vjp``.

No counterpart in the reference: it has no op layer at all (SURVEY.md §1
"no ops/kernel layer" — Keras/Theano supplied kernels).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# newer pallas renamed TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

# The fp32 intermediates of a whole-image block exceed the default 16 MB
# scoped-VMEM budget at the ResNet stem ([12544, 64]); v5e has 128 MB of
# VMEM, so grant the kernels a generous slice of it.
_VMEM_LIMIT = _CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)


def _group_mask(channels: int, groups: int) -> np.ndarray:
    """[C, G] 0/1 matrix: mask[c, g] = 1 iff channel c belongs to group g."""
    if channels % groups:
        raise ValueError(f"channels={channels} not divisible by "
                         f"groups={groups}")
    cg = channels // groups
    mask = np.zeros((channels, groups), np.float32)
    for g in range(groups):
        mask[g * cg:(g + 1) * cg, g] = 1.0
    return mask


def _fwd_kernel(x_ref, gamma_ref, beta_ref, mask_ref, y_ref,
                mean_ref, inv_ref, *, eps: float, relu: bool,
                count: float):
    x = x_ref[0].astype(jnp.float32)                       # [HW, C]
    mask = mask_ref[:]                                     # [C, G]
    s1 = jnp.sum(x, axis=0, keepdims=True)                 # [1, C]
    s2 = jnp.sum(x * x, axis=0, keepdims=True)             # [1, C]
    g1 = jnp.dot(s1, mask, preferred_element_type=jnp.float32)  # [1, G]
    g2 = jnp.dot(s2, mask, preferred_element_type=jnp.float32)  # [1, G]
    mean_g = g1 / count
    var_g = jnp.maximum(g2 / count - mean_g * mean_g, 0.0)
    inv_g = jax.lax.rsqrt(var_g + eps)                     # [1, G]
    # broadcast per-group stats back to channels: [1, G] @ [G, C]
    mean_c = jnp.dot(mean_g, mask.T,
                     preferred_element_type=jnp.float32)   # [1, C]
    inv_c = jnp.dot(inv_g, mask.T,
                    preferred_element_type=jnp.float32)    # [1, C]
    scale = inv_c * gamma_ref[:]                           # [1, C]
    shift = beta_ref[:] - mean_c * scale
    y = x * scale + shift
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.astype(y_ref.dtype)
    mean_ref[0] = mean_g
    inv_ref[0] = inv_g


def _bwd_kernel(x_ref, dy_ref, gamma_ref, beta_ref, mask_ref,
                mean_ref, inv_ref, dx_ref, dgamma_ref, dbeta_ref, *,
                relu: bool, count: float):
    x = x_ref[0].astype(jnp.float32)                       # [HW, C]
    dy = dy_ref[0].astype(jnp.float32)                     # [HW, C]
    mask = mask_ref[:]                                     # [C, G]
    gamma = gamma_ref[:]                                   # [1, C]
    mean_c = jnp.dot(mean_ref[0], mask.T,
                     preferred_element_type=jnp.float32)   # [1, C]
    inv_c = jnp.dot(inv_ref[0], mask.T,
                    preferred_element_type=jnp.float32)    # [1, C]
    xhat = (x - mean_c) * inv_c                            # [HW, C]
    if relu:
        # recompute the pre-ReLU output's sign to mask the cotangent
        z = xhat * gamma + beta_ref[:]
        dy = jnp.where(z > 0, dy, 0.0)
    dgamma_ref[0] = jnp.sum(dy * xhat, axis=0, keepdims=True)  # [1, C]
    dbeta_ref[0] = jnp.sum(dy, axis=0, keepdims=True)          # [1, C]
    dyg = dy * gamma                                       # [HW, C]
    t1 = jnp.dot(jnp.sum(dyg, axis=0, keepdims=True), mask,
                 preferred_element_type=jnp.float32)       # [1, G]
    t2 = jnp.dot(jnp.sum(dyg * xhat, axis=0, keepdims=True), mask,
                 preferred_element_type=jnp.float32)       # [1, G]
    t1_c = jnp.dot(t1, mask.T, preferred_element_type=jnp.float32)
    t2_c = jnp.dot(t2, mask.T, preferred_element_type=jnp.float32)
    dx = inv_c * (dyg - t1_c / count - xhat * (t2_c / count))
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _fwd_call(x3, gamma, beta, mask, *, eps, relu, interpret):
    b, hw, c = x3.shape
    groups = mask.shape[1]
    count = float(hw * (c // groups))
    kernel = functools.partial(_fwd_kernel, eps=eps, relu=relu,
                               count=count)
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, groups), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hw, c), x3.dtype),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
        ],
        compiler_params=None if interpret else _VMEM_LIMIT,
        interpret=interpret,
    )(x3, gamma, beta, mask)


def _bwd_call(x3, dy3, gamma, beta, mask, mean, inv, *, relu, interpret):
    b, hw, c = x3.shape
    groups = mask.shape[1]
    count = float(hw * (c // groups))
    kernel = functools.partial(_bwd_kernel, relu=relu, count=count)
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, groups), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hw, c), x3.dtype),
            jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
        ],
        compiler_params=None if interpret else _VMEM_LIMIT,
        interpret=interpret,
    )(x3, dy3, gamma, beta, mask, mean, inv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _group_norm_3d(x3, gamma, beta, groups, eps, relu, interpret):
    mask = jnp.asarray(_group_mask(x3.shape[-1], groups))
    y, _, _ = _fwd_call(x3, gamma, beta, mask, eps=eps, relu=relu,
                        interpret=interpret)
    return y


def _group_norm_3d_fwd(x3, gamma, beta, groups, eps, relu, interpret):
    mask = jnp.asarray(_group_mask(x3.shape[-1], groups))
    y, mean, inv = _fwd_call(x3, gamma, beta, mask, eps=eps, relu=relu,
                             interpret=interpret)
    return y, (x3, gamma, beta, mask, mean, inv)


def _group_norm_3d_bwd(groups, eps, relu, interpret, residuals, dy):
    x3, gamma, beta, mask, mean, inv = residuals
    dx, dgamma_b, dbeta_b = _bwd_call(
        x3, dy, gamma, beta, mask, mean, inv, relu=relu,
        interpret=interpret)
    dgamma = jnp.sum(dgamma_b, axis=0)  # [B, 1, C] -> [1, C]
    dbeta = jnp.sum(dbeta_b, axis=0)
    return dx, dgamma, dbeta


_group_norm_3d.defvjp(_group_norm_3d_fwd, _group_norm_3d_bwd)


def fused_group_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array, *,
                     groups: int, eps: float = 1e-6, relu: bool = False,
                     interpret: bool | None = None) -> jax.Array:
    """Single-pass GroupNorm with fused affine + optional ReLU.

    ``x``: [B, ..., C] (any number of spatial dims, channels last).
    ``gamma``/``beta``: [C] float32.  Differentiable in x/gamma/beta via
    hand-written backward kernels.  ``interpret`` selects the Pallas
    interpreter; the default (None) auto-enables it off-TPU so the op is
    runnable (slowly) everywhere.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    shape = x.shape
    c = shape[-1]
    b = shape[0]
    hw = int(np.prod(shape[1:-1])) if len(shape) > 2 else 1
    x3 = x.reshape(b, hw, c)
    gamma2 = gamma.reshape(1, c).astype(jnp.float32)
    beta2 = beta.reshape(1, c).astype(jnp.float32)
    y3 = _group_norm_3d(x3, gamma2, beta2, groups, float(eps), bool(relu),
                        bool(interpret))
    return y3.reshape(shape)


def group_norm_reference(x, gamma, beta, *, groups, eps=1e-6,
                         relu=False):
    """Pure-jnp reference (numerics oracle for the kernel tests)."""
    shape = x.shape
    c = shape[-1]
    xf = x.astype(jnp.float32).reshape(shape[0], -1, groups, c // groups)
    mean = xf.mean(axis=(1, 3), keepdims=True)
    var = xf.var(axis=(1, 3), keepdims=True)
    xhat = ((xf - mean) / jnp.sqrt(var + eps)).reshape(shape)
    y = xhat * gamma.reshape((1,) * (len(shape) - 1) + (c,)) \
        + beta.reshape((1,) * (len(shape) - 1) + (c,))
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)

"""Loss functions, resolvable by Keras-style string names.

The reference passes Keras loss names through ``model.compile(loss=...)``
(SURVEY.md §3.1); trainers here accept the same strings (or any callable
``(logits, labels) -> scalar``).  All losses reduce to a batch mean and
compute in float32 regardless of model compute dtype.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import optax

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def categorical_crossentropy(logits: jnp.ndarray,
                             labels: jnp.ndarray) -> jnp.ndarray:
    """Cross-entropy from logits.  Accepts integer class labels (any
    leading shape, e.g. [B] or [B, T]) or one-hot/soft labels with the
    same shape as ``logits``."""
    logits = logits.astype(jnp.float32)
    if labels.ndim == logits.ndim:
        per = optax.softmax_cross_entropy(logits,
                                          labels.astype(jnp.float32))
    else:
        per = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.astype(jnp.int32))
    return per.mean()


def binary_crossentropy(logits: jnp.ndarray,
                        labels: jnp.ndarray) -> jnp.ndarray:
    """Sigmoid cross-entropy from a single logit per row."""
    logits = jnp.squeeze(logits.astype(jnp.float32), axis=-1) \
        if logits.ndim > labels.ndim else logits.astype(jnp.float32)
    return optax.sigmoid_binary_cross_entropy(
        logits, labels.astype(jnp.float32)).mean()


def mean_squared_error(pred: jnp.ndarray,
                       target: jnp.ndarray) -> jnp.ndarray:
    pred = pred.astype(jnp.float32)
    return jnp.mean(jnp.square(pred - target.astype(jnp.float32)))


def mean_absolute_error(pred: jnp.ndarray,
                        target: jnp.ndarray) -> jnp.ndarray:
    pred = pred.astype(jnp.float32)
    return jnp.mean(jnp.abs(pred - target.astype(jnp.float32)))


LOSSES: dict[str, LossFn] = {
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
}


def resolve_loss(loss: str | LossFn) -> LossFn:
    if callable(loss):
        return loss
    if loss not in LOSSES:
        raise KeyError(f"unknown loss {loss!r}; known: {sorted(LOSSES)}")
    return LOSSES[loss]

"""Hand-written Pallas TPU flash attention (forward + backward kernels).

The device-local blockwise path (``parallel.ring_attention.
blockwise_attention``) already avoids materializing the ``[T, T]``
attention matrix, but it is *composed* from XLA ops: ``lax.map`` over q
chunks dispatches one fused region per chunk, and every intermediate
(logits block, probabilities, correction factors) round-trips through
XLA's layout choices.  This module is the same online-softmax algorithm
as ONE Mosaic kernel per pass: the q block, the running max/denominator
and the output accumulator stay resident in VMEM across all k blocks,
k/v blocks stream through the Pallas grid pipeline (double-buffered HBM
fetches overlapping the MXU matmuls), and causally-dead blocks are
skipped by grid predication rather than masked arithmetic.

Numerics match ``models.transformer.dense_causal_attention`` up to
reduction order: logit/softmax statistics accumulate in f32; the
probabilities are cast back to the input dtype for the P·V / dS·K
matmuls exactly as the dense path's ``probs.astype(q.dtype)`` does.

The backward pass is the standard flash decomposition (recompute
probabilities from the saved logsumexp): one kernel accumulates dQ with
k/v blocks streaming, one accumulates dK/dV with q blocks streaming, and
the softmax-jacobian diagonal ``D = rowsum(dO * O)`` is precomputed
outside the kernels (one cheap fused elementwise-reduce).

No counterpart in the reference: it has no op layer at all (SURVEY.md §1
"no ops/kernel layer" — Keras/Theano supplied kernels), let alone an
attention one.  A/B against the scan-composed blockwise path is in
PERF.md §17.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30

# Measured v5e optimum of the round-4 sweep at T=2048 (PERF.md §17).
_DEFAULT_BLOCK_Q = 512
_DEFAULT_BLOCK_K = 1024

# 3 parallel grid dims (batch, head, q block) + 1 sequential reduction
# dim (k or q block stream) that the VMEM accumulators persist across.
_SEMANTICS = ("parallel", "parallel", "parallel", "arbitrary")


def _params(semantics=_SEMANTICS):
    # newer pallas renamed TPUCompilerParams -> CompilerParams
    cp = getattr(pltpu, "CompilerParams",
                 getattr(pltpu, "TPUCompilerParams", None))
    return cp(dimension_semantics=semantics)


def _causal_mask(i, j, bq, bk):
    """[bq, bk] boolean: query row i*bq+r attends key col j*bk+c."""
    rows = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


def _j_last(i, bq, bk, n_k, causal):
    """Index of the last k block the i-th q block attends to."""
    if not causal:
        return n_k - 1
    # int32 throughout: x64 mode must not promote in-kernel index math
    return jnp.minimum(((i * bq + bq - 1) // bk).astype(jnp.int32),
                       jnp.int32(n_k - 1))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, scale, causal, n_k):
    i, j = pl.program_id(2), pl.program_id(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Blocks entirely above the causal diagonal contribute nothing.
    @pl.when(jnp.logical_or(not causal, j * bk <= i * bq + bq - 1))
    def _():
        q = q_ref[0, 0]                                    # [bq, D]
        k = k_ref[0, 0]                                    # [bk, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, bk]
        if causal:
            logits = jnp.where(_causal_mask(i, j, bq, bk), logits,
                               _NEG)
        m_prev, l_prev = m_scr[:], l_scr[:]                # [bq, 1]
        m_new = jnp.maximum(m_prev,
                            jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)                        # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                     # [bq, 1]
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, D]

    @pl.when(j == _j_last(i, bq, bk, n_k, causal))
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:] + jnp.log(l)).astype(jnp.float32)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, dq_ref,
               dq_scr, *, scale, causal, n_k):
    i, j = pl.program_id(2), pl.program_id(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(jnp.logical_or(not causal, j * bk <= i * bq + bq - 1))
    def _():
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]                                  # [bq, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(i, j, bq, bk)
            logits = jnp.where(mask, logits, _NEG)
        p = jnp.exp(logits - lse_ref[0, 0])                # [bq, bk]
        if causal:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - dsum_ref[0, 0]) * scale             # [bq, bk]
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, D]

    @pl.when(j == _j_last(i, bq, bk, n_k, causal))
    def _():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                n_q):
    j, i = pl.program_id(2), pl.program_id(3)
    bk, bq = k_ref.shape[2], q_ref.shape[2]

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(jnp.logical_or(not causal, i * bq + bq - 1 >= j * bk))
    def _():
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]                                  # [bq, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, bk]
        if causal:
            mask = _causal_mask(i, j, bq, bk)
            logits = jnp.where(mask, logits, _NEG)
        p = jnp.exp(logits - lse_ref[0, 0])                # [bq, bk]
        if causal:
            p = jnp.where(mask, p, 0.0)
        pt = p.astype(do.dtype)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = (p * (dp - dsum_ref[0, 0]) * scale).astype(q.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, D]

    @pl.when(i == n_q - 1)
    def _():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _auto_block(t, target):
    """Largest divisor of ``t`` that is <= ``target`` (t <= target
    short-circuits to t).  Sequence lengths are multiples of 128 in
    practice, so this lands on an MXU-friendly size (e.g. T=768 ->
    384, T=1280 -> 640); degenerate T degrades gracefully."""
    b = min(target, t)
    while t % b:
        b -= 1
    return b


def _blocks_pair(t, tk, block_q, block_k):
    """(block_q, block_k) for q length ``t`` and k length ``tk``:
    defaults auto-clamp to the largest divisor <= the measured
    optimum; explicit values are clamped to the length and must then
    divide it."""
    bq = _auto_block(t, _DEFAULT_BLOCK_Q) if block_q is None \
        else min(block_q, t)
    bk = _auto_block(tk, _DEFAULT_BLOCK_K) if block_k is None \
        else min(block_k, tk)
    if t % bq or tk % bk:
        raise ValueError(
            f"lengths ({t}, {tk}) must be divisible by "
            f"block_q={bq} and block_k={bk} (pass block_q/block_k="
            f"None to auto-pick divisors)")
    return bq, bk


def _blocks(t, block_q, block_k):
    return _blocks_pair(t, t, block_q, block_k)


def _check_mosaic_alignment(bq, bk, t, tk):
    """Compiled Mosaic requires lane/sublane-aligned tiles; an
    unaligned auto-picked block (e.g. prime or odd T, where the
    largest divisor degrades toward 1) fails deep in the compiler
    with an opaque tiling error.  Catch it here with an actionable
    one.  The interpreter path accepts any block, so this only runs
    when compiling (interpret=False)."""
    if bq % 8 or bk % 8:
        raise ValueError(
            f"sequence lengths ({t}, {tk}) have no MXU-aligned "
            f"divisor <= the block targets (picked block_q={bq}, "
            f"block_k={bk}); compiled Mosaic needs blocks that are "
            "multiples of 8 (ideally 128).  Pad the sequence to a "
            "multiple of 128, or pass explicit aligned "
            "block_q/block_k that divide it.")


def _qblk(bq, d):
    """BlockSpec for a per-(b, h, i) q-shaped operand on [B, H, T, D]."""
    return pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0),
                        memory_space=pltpu.VMEM)


def _kblk(bk, d):
    """BlockSpec for a per-(b, h, j) k-shaped operand on [B, H, T, D]."""
    return pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0),
                        memory_space=pltpu.VMEM)


def _rowblk(bq):
    """BlockSpec for a per-(b, h, i) row statistic on [B, H, T, 1]."""
    return pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                        memory_space=pltpu.VMEM)


def _fwd_call(q, k, v, scale, causal, bq, bk, interpret):
    b, h, t, d = q.shape
    n_q, n_k = t // bq, t // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               n_k=n_k)
    return pl.pallas_call(
        kernel,
        grid=(b, h, n_q, n_k),
        in_specs=[_qblk(bq, d), _kblk(bk, d), _kblk(bk, d)],
        out_specs=[_qblk(bq, d), _rowblk(bq)],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=None if interpret else _params(),
        interpret=interpret,
    )(q, k, v)


def _bwd_call(q, k, v, do, lse, dsum, scale, causal, bq, bk,
              interpret):
    b, h, t, d = q.shape
    n_q, n_k = t // bq, t // bk
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          n_k=n_k),
        grid=(b, h, n_q, n_k),
        in_specs=[_qblk(bq, d), _kblk(bk, d), _kblk(bk, d),
                  _qblk(bq, d), _rowblk(bq), _rowblk(bq)],
        out_specs=[_qblk(bq, d)],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=None if interpret else _params(),
        interpret=interpret,
    )(q, k, v, do, lse, dsum)[0]

    # dK/dV: the k block is the resident operand, q blocks stream.
    kspec = pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b, h, j, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    rspec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          n_q=n_q),
        grid=(b, h, n_k, n_q),
        in_specs=[qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, t, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=None if interpret else _params(),
        interpret=interpret,
    )(q, k, v, do, lse, dsum)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhtd(q, k, v, scale, causal, bq, bk, interpret):
    out, _ = _fwd_call(q, k, v, scale, causal, bq, bk, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, bq, bk, interpret):
    out, lse = _fwd_call(q, k, v, scale, causal, bq, bk, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, bq, bk, interpret, residuals, dout):
    q, k, v, out, lse = residuals
    # Softmax-jacobian diagonal, one fused elementwise-reduce in XLA.
    dsum = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1, keepdims=True)                 # [B, H, T, 1]
    dq, dk, dv = _bwd_call(q, k, v, dout.astype(q.dtype), lse, dsum,
                           scale, causal, bq, bk, interpret)
    return dq, dk, dv


_flash_bhtd.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    scale: float | None = None, causal: bool = True,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None) -> jax.Array:
    """Pallas-kernel attention: ``[B, T, H, D] -> [B, T, H, D]``.

    Same contract as ``models.transformer.dense_causal_attention`` and
    ``parallel.ring_attention.blockwise_attention``; differentiable via
    hand-written backward kernels (first-order only).  ``block_q``/
    ``block_k`` default to the measured v5e optimum (512/1024) clamped
    to the largest divisor of T, so any sequence length works; an
    explicit value is first clamped down to T (a block cannot exceed
    the sequence) and must then divide T — anything else raises.
    ``interpret`` defaults to auto: the Pallas interpreter off-TPU so
    tests run anywhere, compiled Mosaic on TPU.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bq, bk = _blocks(q.shape[1], block_q, block_k)
    if not interpret:
        _check_mosaic_alignment(bq, bk, q.shape[1], q.shape[1])
    # [B, T, H, D] -> [B, H, T, D]: one transpose each way per pass —
    # negligible (O(T)) next to attention's O(T^2), and it gives the
    # kernels their natural (rows = time, lanes = head_dim) layout.
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out = _flash_bhtd(qt, kt, vt, float(scale), bool(causal), bq, bk,
                      bool(interpret))
    return jnp.swapaxes(out, 1, 2)


def flash_attn_fn(causal: bool = True, block_q: int | None = None,
                  block_k: int | None = None):
    """An ``AttnFn`` (``TransformerLM.attn_fn`` signature) running the
    Pallas flash kernels.  Block defaults are the measured v5e optimum
    of the round-4 sweep at T=2048 (PERF.md §17: 512/1024 -> 10.1 ms
    fwd+bwd vs 16.8 ms scan-blockwise, 17.8 ms dense), auto-clamped to
    divisors of T."""
    return functools.partial(flash_attention, causal=causal,
                             block_q=block_q, block_k=block_k)


# ---------------------------------------------------------------------
# Ring-hop kernels: the same online-softmax kernels with (a) the
# softmax state (m, l, acc) carried IN and OUT instead of finalized,
# and (b) global position offsets for q and k supplied as scalars —
# one call processes one ring hop's K/V block against the local q
# block, so sequence parallelism (parallel.ring_attention) can run
# the Pallas path per hop while the ring carries the state between
# devices.  Offsets are SMEM scalar inputs because they are traced
# values inside the ring's lax.scan (the hop source rotates).
# ---------------------------------------------------------------------


def _off_mask(qo, ko, i, j, bq, bk):
    """[bq, bk] causal mask in GLOBAL positions (qo/ko are scalars)."""
    rows = qo + i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ko + j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


def _hop_fwd_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, m_in_ref,
                    l_in_ref, acc_in_ref, m_ref, l_ref, acc_ref,
                    m_scr, l_scr, acc_scr, *, scale, causal, n_k):
    i, j = pl.program_id(2), pl.program_id(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    qo, ko = qo_ref[0], ko_ref[0]

    @pl.when(j == 0)
    def _():
        m_scr[:] = m_in_ref[0, 0]
        l_scr[:] = l_in_ref[0, 0]
        acc_scr[:] = acc_in_ref[0, 0]

    # a block contributes unless causally dead in global positions
    alive = jnp.logical_or(
        not causal, ko + j * bk <= qo + i * bq + bq - 1)

    @pl.when(alive)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            logits = jnp.where(_off_mask(qo, ko, i, j, bq, bk),
                               logits, _NEG)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev,
                            jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_k - 1)
    def _():
        m_ref[0, 0] = m_scr[:]
        l_ref[0, 0] = l_scr[:]
        acc_ref[0, 0] = acc_scr[:]


def _hop_dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, dsum_ref, dq_ref, dq_scr, *, scale,
                   causal, n_k):
    i, j = pl.program_id(2), pl.program_id(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    qo, ko = qo_ref[0], ko_ref[0]

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    alive = jnp.logical_or(
        not causal, ko + j * bk <= qo + i * bq + bq - 1)

    @pl.when(alive)
    def _():
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _off_mask(qo, ko, i, j, bq, bk)
            logits = jnp.where(mask, logits, _NEG)
        p = jnp.exp(logits - lse_ref[0, 0])
        if causal:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dsum_ref[0, 0]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_k - 1)
    def _():
        dq_ref[0, 0] = dq_scr[:]


def _hop_dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, dsum_ref, dk_ref, dv_ref, dk_scr,
                    dv_scr, *, scale, causal, n_q):
    j, i = pl.program_id(2), pl.program_id(3)
    bk, bq = k_ref.shape[2], q_ref.shape[2]
    qo, ko = qo_ref[0], ko_ref[0]

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    alive = jnp.logical_or(
        not causal, qo + i * bq + bq - 1 >= ko + j * bk)

    @pl.when(alive)
    def _():
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _off_mask(qo, ko, i, j, bq, bk)
            logits = jnp.where(mask, logits, _NEG)
        p = jnp.exp(logits - lse_ref[0, 0])
        if causal:
            p = jnp.where(mask, p, 0.0)
        pt = p.astype(do.dtype)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - dsum_ref[0, 0]) * scale).astype(q.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_q - 1)
    def _():
        dk_ref[0, 0] = dk_scr[:]
        dv_ref[0, 0] = dv_scr[:]


def _struct(vma, shape):
    """f32 ShapeDtypeStruct, tagged varying-over-``vma`` mesh axes
    when given (required for pallas outputs under shard_map's
    check_vma).  Older jax has no vma type system (its ShapeDtypeStruct
    rejects the kwarg) — the tag only exists for the checker, so it is
    simply dropped there."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, jnp.float32)
    try:
        return jax.ShapeDtypeStruct(shape, jnp.float32,
                                    vma=frozenset(vma))
    except TypeError:  # old jax: no vma kwarg (and no checker)
        return jax.ShapeDtypeStruct(shape, jnp.float32)


def _scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def flash_hop_fwd(q, k, v, m, l, acc, *, q_offset, k_offset,
                  scale, causal=True, block_q=None, block_k=None,
                  vma=None, interpret=None):
    """One ring hop of flash attention, state carried.

    All arrays are [B, H, T, D]-layout blocks local to this device:
    ``q`` is the resident query block; ``k``/``v`` the visiting hop's
    K/V block; ``m``/``l`` [B, H, T, 1] and ``acc`` [B, H, T, D] the
    running online-softmax state (f32).  ``q_offset``/``k_offset`` are
    the blocks' global time positions (traced scalars are fine).
    Returns the updated ``(m, l, acc)``.  The caller finalizes with
    ``out = acc / max(l, eps)`` and ``lse = m + log l`` after the last
    hop.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, h, t, d = q.shape
    tk = k.shape[2]
    bq, bk = _blocks_pair(t, tk, block_q, block_k)
    if not interpret:
        _check_mosaic_alignment(bq, bk, t, tk)
    n_q, n_k = t // bq, tk // bk
    kernel = functools.partial(_hop_fwd_kernel, scale=scale,
                               causal=causal, n_k=n_k)
    qo = jnp.asarray(q_offset, jnp.int32).reshape(1)
    ko = jnp.asarray(k_offset, jnp.int32).reshape(1)
    out = functools.partial(_struct, vma)
    return pl.pallas_call(
        kernel,
        grid=(b, h, n_q, n_k),
        in_specs=[_scalar_spec(), _scalar_spec(),
                  _qblk(bq, d), _kblk(bk, d), _kblk(bk, d),
                  _qblk(bq, 1), _qblk(bq, 1), _qblk(bq, d)],
        out_specs=[_qblk(bq, 1), _qblk(bq, 1), _qblk(bq, d)],
        out_shape=[out((b, h, t, 1)), out((b, h, t, 1)),
                   out((b, h, t, d))],
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=None if interpret else _params(),
        interpret=interpret,
    )(qo, ko, q, k, v, m, l, acc)


def flash_hop_bwd(q, k, v, do, lse, dsum, *, q_offset, k_offset,
                  scale, causal=True, block_q=None, block_k=None,
                  vma=None, interpret=None):
    """One ring hop of the flash backward: partial ``(dq, dk, dv)``
    for this (local q)×(visiting k/v) pair, to be accumulated by the
    caller (dq locally; dk/dv riding the ring with their block).
    ``lse`` [B, H, T, 1] is the FINAL logsumexp; ``dsum`` [B, H, T, 1]
    is rowsum(dO·O)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, h, t, d = q.shape
    tk = k.shape[2]
    bq, bk = _blocks_pair(t, tk, block_q, block_k)
    if not interpret:
        _check_mosaic_alignment(bq, bk, t, tk)
    n_q, n_k = t // bq, tk // bk
    qo = jnp.asarray(q_offset, jnp.int32).reshape(1)
    ko = jnp.asarray(k_offset, jnp.int32).reshape(1)
    dq = pl.pallas_call(
        functools.partial(_hop_dq_kernel, scale=scale, causal=causal,
                          n_k=n_k),
        grid=(b, h, n_q, n_k),
        in_specs=[_scalar_spec(), _scalar_spec(),
                  _qblk(bq, d), _kblk(bk, d), _kblk(bk, d),
                  _qblk(bq, d), _qblk(bq, 1), _qblk(bq, 1)],
        out_specs=[_qblk(bq, d)],
        out_shape=[_struct(vma, (b, h, t, d))],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=None if interpret else _params(),
        interpret=interpret,
    )(qo, ko, q, k, v, do, lse, dsum)[0]

    kspec = pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b, h, j, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    rspec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_hop_dkv_kernel, scale=scale, causal=causal,
                          n_q=n_q),
        grid=(b, h, n_k, n_q),
        in_specs=[_scalar_spec(), _scalar_spec(),
                  qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=[kspec, kspec],
        out_shape=[_struct(vma, (b, h, tk, d)),
                   _struct(vma, (b, h, tk, d))],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=None if interpret else _params(),
        interpret=interpret,
    )(qo, ko, q, k, v, do, lse, dsum)
    return dq, dk, dv




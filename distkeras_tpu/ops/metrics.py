"""Eval metrics (jittable).  The reference delegated evaluation to
pyspark.ml evaluators in notebooks (SURVEY.md §2.1 Evaluators); here they
are plain functions used by ``distkeras_tpu.evaluators``."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Fraction of rows whose argmax matches the integer label."""
    pred = jnp.argmax(logits, axis=-1)
    return jnp.mean((pred == labels.astype(pred.dtype))
                    .astype(jnp.float32))


def binary_accuracy(logits: jnp.ndarray,
                    labels: jnp.ndarray) -> jnp.ndarray:
    logits = jnp.squeeze(logits, axis=-1) if logits.ndim > labels.ndim \
        else logits
    pred = (logits > 0).astype(jnp.int32)
    return jnp.mean((pred == labels.astype(jnp.int32))
                    .astype(jnp.float32))


def top_k_accuracy(logits: jnp.ndarray, labels: jnp.ndarray,
                   k: int = 5) -> jnp.ndarray:
    _, top = jax.lax.top_k(logits, k)
    hit = jnp.any(top == labels[..., None].astype(top.dtype), axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


def perplexity(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """exp(mean next-token cross-entropy) — the LM eval metric.

    ``logits``: ``[..., V]``; ``labels``: integer ids matching the
    leading shape.  Uniform logits give exactly ``V``; a perfect model
    gives 1.  Exponentiates the SAME cross-entropy the trainers
    minimize (``ops.losses.categorical_crossentropy``), so eval ppl
    and training loss can never silently diverge.
    """
    from distkeras_tpu.ops.losses import categorical_crossentropy

    return jnp.exp(categorical_crossentropy(logits, labels))


def auc_roc(scores: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Area under the ROC curve via the Mann-Whitney U statistic
    (rank-based, tie-aware) — the ``pyspark.ml``
    ``BinaryClassificationEvaluator('areaUnderROC')`` surface for the
    Criteo-style binary configs.  ``scores`` are any monotone ranking
    (logits or probabilities); ``labels`` in {0, 1}.  Under jit a
    single-class batch yields NaN; on concrete inputs, bad labels or a
    single-class input raise a clear error instead."""
    scores = scores.reshape(-1).astype(jnp.float32)
    labels = labels.reshape(-1).astype(jnp.float32)
    if not isinstance(labels, jax.core.Tracer):
        import numpy as np

        l = np.asarray(labels)
        if l.size and not np.isin(l, (0.0, 1.0)).all():
            raise ValueError(
                f"auc_roc needs labels in {{0, 1}}, got values in "
                f"[{l.min()}, {l.max()}]")
        if l.size and (l.min() == l.max()):
            raise ValueError(
                "auc_roc needs both classes present, got only "
                f"label {l.min()}")
    sorted_scores = jnp.sort(scores)
    # tie-aware average rank (1-based): mean of the left/right insertion
    # positions among the sorted scores
    lo = jnp.searchsorted(sorted_scores, scores, side="left")
    hi = jnp.searchsorted(sorted_scores, scores, side="right")
    ranks = (lo + hi + 1.0) / 2.0
    pos = labels.sum()
    neg = labels.shape[0] - pos
    u = (ranks * labels).sum() - pos * (pos + 1.0) / 2.0
    # single-class input (reachable only under jit, where the concrete
    # check is skipped) is NaN, not a fake 0.0
    return jnp.where(pos * neg > 0,
                     u / jnp.maximum(pos * neg, 1e-30), jnp.nan)


def macro_auc_roc(scores: jnp.ndarray, labels: jnp.ndarray,
                  num_classes: int | None = None) -> jnp.ndarray:
    """One-vs-rest macro-averaged AUC-ROC for multi-class scores — the
    ranking metric the binary configs get from ``auc_roc``, extended to
    the multi-class baseline configs (SURVEY.md §5 metrics row).

    ``scores`` is ``[N, C]`` (logits or probabilities — any per-class
    monotone ranking); ``labels`` are integer class ids ``[N]``.  Each
    class c scores ``auc_roc(scores[:, c], labels == c)``; the macro
    average weights every class equally (the sklearn
    ``roc_auc_score(..., multi_class='ovr', average='macro')``
    convention).  On concrete inputs a class with no positive or no
    negative rows raises (its one-vs-rest AUC is undefined); under jit
    such a class contributes NaN, which poisons the mean rather than
    silently shrinking the denominator."""
    if scores.ndim != 2:
        raise ValueError(
            f"macro_auc_roc needs [N, C] per-class scores, got shape "
            f"{scores.shape}")
    n_cls = num_classes if num_classes is not None else scores.shape[-1]
    if n_cls != scores.shape[-1]:
        raise ValueError(
            f"num_classes={n_cls} does not match score width "
            f"{scores.shape[-1]}")
    if n_cls < 2:
        raise ValueError("macro_auc_roc needs at least 2 classes; use "
                         "auc_roc for single-score binary rows")
    labels = labels.reshape(-1)
    if not isinstance(labels, jax.core.Tracer):
        import numpy as np

        l = np.asarray(labels).astype(np.int64)
        if l.size and (l.min() < 0 or l.max() >= n_cls):
            raise ValueError(
                f"label ids out of range [0, {n_cls}): labels in "
                f"[{l.min()}, {l.max()}] — pass num_classes (or widen "
                f"the score matrix) to cover every class")
        counts = np.bincount(l, minlength=n_cls)
        missing = [c for c in range(n_cls)
                   if counts[c] == 0 or counts[c] == labels.shape[0]]
        if missing:
            raise ValueError(
                f"one-vs-rest AUC is undefined for classes {missing}: "
                f"each class needs both positive and negative rows in "
                f"the evaluated split")
    # one vectorized rank computation over all classes (a Python loop
    # would dispatch C sorts and unroll C copies under jit)
    masks = (labels[None, :] == jnp.arange(n_cls)[:, None]).astype(
        jnp.float32)                                     # [C, N]
    per_class = jax.vmap(auc_roc, in_axes=(1, 0))(scores, masks)
    return jnp.mean(per_class)


def confusion_matrix(pred: jnp.ndarray, labels: jnp.ndarray,
                     num_classes: int) -> jnp.ndarray:
    """``[C, C]`` counts, rows = true class, cols = predicted class.
    ``num_classes`` must be static (jit-compatible bincount).  Class
    ids must lie in ``[0, num_classes)`` — validated on concrete
    (non-traced) inputs; under jit the bound is the caller's contract
    (bincount would silently drop out-of-range rows)."""
    if not isinstance(pred, jax.core.Tracer) \
            and not isinstance(labels, jax.core.Tracer):
        import numpy as np

        p, l = np.asarray(pred), np.asarray(labels)
        if p.size and (p.min() < 0 or p.max() >= num_classes
                       or l.min() < 0 or l.max() >= num_classes):
            raise ValueError(
                f"class ids out of range [0, {num_classes}): "
                f"pred in [{p.min()}, {p.max()}], "
                f"labels in [{l.min()}, {l.max()}]")
    idx = (labels.astype(jnp.int32) * num_classes
           + pred.astype(jnp.int32))
    return jnp.bincount(
        idx.reshape(-1),
        length=num_classes * num_classes).reshape(num_classes,
                                                  num_classes)


def precision_recall_f1(pred: jnp.ndarray, labels: jnp.ndarray,
                        num_classes: int, average: str = "weighted"
                        ) -> dict[str, jnp.ndarray]:
    """Multi-class precision / recall / F1 from class-id predictions
    (the ``pyspark.ml`` ``MulticlassClassificationEvaluator`` surface
    the reference notebooks leaned on — SURVEY.md §2.1 Evaluators).

    ``average``: ``'weighted'`` (pyspark's default: class scores
    weighted by true-class frequency), ``'macro'`` (unweighted class
    mean), or ``'micro'`` (global counts; equals accuracy for
    single-label classification).  Classes with no predictions (or no
    true rows) score 0, the standard zero-division convention.
    """
    cm = confusion_matrix(pred, labels, num_classes).astype(jnp.float32)
    tp = jnp.diagonal(cm)
    pred_tot = cm.sum(axis=0)
    true_tot = cm.sum(axis=1)
    if average == "micro":
        total = jnp.maximum(cm.sum(), 1.0)
        p = r = tp.sum() / total
        f1 = p
        return {"precision": p, "recall": r, "f1": f1}
    prec = jnp.where(pred_tot > 0, tp / jnp.maximum(pred_tot, 1.0), 0.0)
    rec = jnp.where(true_tot > 0, tp / jnp.maximum(true_tot, 1.0), 0.0)
    denom = prec + rec
    f1 = jnp.where(denom > 0, 2.0 * prec * rec
                   / jnp.maximum(denom, 1e-30), 0.0)
    if average == "macro":
        w = jnp.full_like(tp, 1.0 / num_classes)
    elif average == "weighted":
        w = true_tot / jnp.maximum(true_tot.sum(), 1.0)
    else:
        raise ValueError(f"unknown average {average!r}; expected "
                         f"'weighted', 'macro', or 'micro'")
    return {"precision": (prec * w).sum(), "recall": (rec * w).sum(),
            "f1": (f1 * w).sum()}

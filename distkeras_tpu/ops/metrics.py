"""Eval metrics (jittable).  The reference delegated evaluation to
pyspark.ml evaluators in notebooks (SURVEY.md §2.1 Evaluators); here they
are plain functions used by ``distkeras_tpu.evaluators``."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Fraction of rows whose argmax matches the integer label."""
    pred = jnp.argmax(logits, axis=-1)
    return jnp.mean((pred == labels.astype(pred.dtype))
                    .astype(jnp.float32))


def binary_accuracy(logits: jnp.ndarray,
                    labels: jnp.ndarray) -> jnp.ndarray:
    logits = jnp.squeeze(logits, axis=-1) if logits.ndim > labels.ndim \
        else logits
    pred = (logits > 0).astype(jnp.int32)
    return jnp.mean((pred == labels.astype(jnp.int32))
                    .astype(jnp.float32))


def top_k_accuracy(logits: jnp.ndarray, labels: jnp.ndarray,
                   k: int = 5) -> jnp.ndarray:
    _, top = jax.lax.top_k(logits, k)
    hit = jnp.any(top == labels[..., None].astype(top.dtype), axis=-1)
    return jnp.mean(hit.astype(jnp.float32))

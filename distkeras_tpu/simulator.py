"""Production traffic + chaos simulator (ISSUE 18).

Every perf script in this repo pumps one synthetic shape at a fixed
rate; the reference system's core claim is surviving *real* cluster
conditions.  This module closes that gap with a workload harness that
replays parameterized production traces against the full serving stack
and a capacity model fitted from the telemetry it produces:

* ``TraceSpec`` / ``generate_trace`` — a seeded trace generator:
  diurnal rate cycles, flash crowds, heavy-tailed prompt lengths
  (lognormal) and output lengths (Pareto), session-sticky users
  sharing per-group system prefixes (Zipf-distributed session
  popularity), and mixed tenant/priority classes.  The whole arrival +
  length + session + tenant stream is a pure function of
  ``TraceSpec.seed``: the non-homogeneous Poisson process is drawn by
  thinning against the analytic ``rate_at`` curve with one pinned rng,
  fixed draw order per arrival.
* ``replay`` — paces a trace against a ``ServingGateway`` in wall time
  (``time_scale`` compresses or dilates), polling results without ever
  blocking the offered-load clock, while a ``ChaosSchedule`` fires
  wall-clock fault windows (via ``ChaosTransport(windows=...)``) and
  replica/PS ``kill()``s phase-aligned with the load curve —
  fault-during-flash-crowd is the scenario that matters.
* ``stepped_rate_search`` / ``CapacityModel`` — sustainable QPS at a
  fixed TTFT SLO per configuration, found by walking a geometric rate
  ladder until attainment breaks; the fitted model answers
  ``required(qps)`` — the replica target a closed-loop drill holds the
  ``telemetry.Autoscaler`` to.
* ``run_drill`` — the closed-loop acceptance scenario: the autoscaler
  must track ``required(rate_at(t))`` as the curve moves, with
  convergence seconds (``sim_drill_convergence_seconds_total``) and
  the watchdog's ``slo_violation_seconds_total`` as the gated metrics
  (see ``scripts/perf_capacity.py``).

The replay loop is deliberately single-threaded — submissions, result
polling, chaos kills, and autoscaler ticks interleave in ONE pacing
loop — so the simulator itself holds no locks and adds no
nondeterminism beyond the stack under test.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, Mapping, Optional, Sequence

import numpy as np

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.parallel.faults import (ChaosTransport,
                                           _validate_windows)

__all__ = [
    "TraceSpec", "Arrival", "Trace", "generate_trace", "rate_at",
    "peak_rate", "in_crowd", "declared_length_quantiles",
    "ChaosSchedule", "ReplicaPool", "replay", "stepped_rate_search",
    "CapacityPoint", "CapacityModel", "run_drill",
]

#: standard-normal quantile for p99 — the lognormal length model's
#: declared p99 is ``median * exp(sigma * Z99)``
_Z99 = 2.3263478740408408

#: per-tenant length models a ``TraceSpec`` tenant quad may select
#: (the optional 4th tuple element); ``prefill_heavy`` is the long-
#: prompt / short-output flood class the disaggregated serving drill
#: generates natively
TENANT_CLASSES = ("default", "prefill_heavy")


# ---------------------------------------------------------------------
# trace specification + generation
# ---------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """Parameterized production-trace shape.  Everything downstream —
    arrivals, lengths, sessions, tenants — derives from ``seed``
    alone, so a trace is replayable and a chaos drill reproducible.

    Rate curve: ``mean_qps`` modulated by a sinusoidal diurnal cycle
    (``diurnal_amplitude`` in [0, 1); period defaults to the trace
    duration so the integral over the trace matches the requested mean
    exactly) and multiplied inside each flash-crowd window
    ``(t_start, t_end, multiplier)``.

    Lengths: prompts are lognormal (``prompt_median`` tokens median,
    ``prompt_sigma`` log-space sigma) clipped to
    [``prompt_min``, ``prompt_max``]; outputs are Pareto type I
    (``output_min`` scale, ``output_alpha`` tail index — smaller alpha
    = heavier tail; declared p99/p50 ratio is ``50**(1/alpha)``)
    clipped to [``output_min``, ``output_max``].

    Sessions: ``sessions`` users with Zipf(``session_zipf``)
    popularity; each session belongs to one of ``prefix_groups``
    groups sharing a ``prefix_len``-token system prefix (the
    prefix-cache workload shape).

    Tenants: ``(name, share, priority)`` triples — or ``(name, share,
    priority, tenant_class)`` quads — with shares normalized and
    priority riding into the engine QoS scheduler (0..2).  The
    optional class picks the tenant's length model: ``"default"``
    uses the spec-wide prompt/output models above;
    ``"prefill_heavy"`` draws long lognormal prompts
    (``heavy_prompt_median`` / ``heavy_prompt_sigma``) with outputs
    clipped to ``heavy_output_max`` — the prefill-flood workload the
    disaggregated serving drill rides on.  Heavy arrivals take their
    EXTRA length draws after the tenant draw, so a spec without
    heavy tenants generates a byte-identical trace per seed.
    """

    duration_s: float
    mean_qps: float
    seed: int = 0
    diurnal_amplitude: float = 0.0
    diurnal_period_s: Optional[float] = None
    flash_crowds: tuple = ()
    prompt_median: float = 24.0
    prompt_sigma: float = 0.6
    prompt_min: int = 4
    prompt_max: int = 512
    output_alpha: float = 2.0
    output_min: int = 4
    output_max: int = 256
    vocab: int = 1000
    sessions: int = 50
    session_zipf: float = 1.5
    prefix_groups: int = 4
    prefix_len: int = 2
    tenants: tuple = (("default", 1.0, 1),)
    heavy_prompt_median: float = 192.0
    heavy_prompt_sigma: float = 0.35
    heavy_output_max: int = 16

    def __post_init__(self):
        if self.duration_s <= 0 or self.mean_qps <= 0:
            raise ValueError("duration_s and mean_qps must be > 0")
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise ValueError(
                f"diurnal_amplitude={self.diurnal_amplitude} outside "
                f"[0, 1) (the rate must stay positive)")
        for w in self.flash_crowds:
            t0, t1, mult = w
            if not (0.0 <= t0 < t1) or mult <= 0:
                raise ValueError(f"bad flash crowd {w!r}")
        if self.prompt_min < 1 or self.prompt_max < self.prompt_min:
            raise ValueError("need 1 <= prompt_min <= prompt_max")
        if self.prefix_len >= self.prompt_min:
            raise ValueError(
                f"prefix_len={self.prefix_len} must be below "
                f"prompt_min={self.prompt_min} (every prompt carries "
                f"its group prefix plus at least one own token)")
        if self.output_alpha <= 0 or self.output_min < 1:
            raise ValueError("need output_alpha > 0, output_min >= 1")
        if self.output_max < self.output_min:
            raise ValueError("need output_min <= output_max")
        if self.session_zipf <= 1.0:
            raise ValueError("session_zipf must be > 1")
        if self.sessions < 1 or self.prefix_groups < 1:
            raise ValueError("need sessions >= 1, prefix_groups >= 1")
        if not self.tenants:
            raise ValueError("tenants need positive shares")
        for ten in self.tenants:
            if len(ten) not in (3, 4):
                raise ValueError(
                    f"tenant {ten!r} must be (name, share, priority) "
                    f"or (name, share, priority, tenant_class)")
            if ten[1] <= 0:
                raise ValueError("tenants need positive shares")
            if len(ten) == 4 and ten[3] not in TENANT_CLASSES:
                raise ValueError(
                    f"unknown tenant class {ten[3]!r}; choose from "
                    f"{TENANT_CLASSES}")
        if self.heavy_prompt_median < 1:
            raise ValueError("heavy_prompt_median must be >= 1")
        if self.heavy_output_max < self.output_min:
            raise ValueError(
                f"heavy_output_max={self.heavy_output_max} below "
                f"output_min={self.output_min}")


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One trace row: arrival time (trace seconds) plus the request."""

    t: float
    prompt: np.ndarray
    max_new: int
    session: str
    tenant: str
    priority: int


@dataclasses.dataclass(frozen=True)
class Trace:
    spec: TraceSpec
    arrivals: tuple


def rate_at(spec: TraceSpec, t: float) -> float:
    """The analytic offered-rate curve (QPS) at trace time ``t``."""
    period = spec.diurnal_period_s or spec.duration_s
    r = spec.mean_qps * (
        1.0 + spec.diurnal_amplitude
        * math.sin(2.0 * math.pi * t / period))
    for t0, t1, mult in spec.flash_crowds:
        if t0 <= t < t1:
            r *= mult
    return r


def peak_rate(spec: TraceSpec) -> float:
    """An upper bound on ``rate_at`` over the trace — the thinning
    envelope (loose is fine: it only costs rejected candidate
    draws, never correctness)."""
    r = spec.mean_qps * (1.0 + spec.diurnal_amplitude)
    for _, _, mult in spec.flash_crowds:
        r *= max(1.0, mult)
    return r


def in_crowd(spec: TraceSpec, t: float) -> bool:
    return any(t0 <= t < t1 for t0, t1, _ in spec.flash_crowds)


def declared_length_quantiles(spec: TraceSpec) -> dict:
    """The analytic (pre-clipping) p50/p99 of the two length models —
    what the generated stream must reproduce (the heavy-tail
    regression test's reference)."""
    pm = float(spec.prompt_median)
    return {
        "prompt_p50": pm,
        "prompt_p99": pm * math.exp(spec.prompt_sigma * _Z99),
        "output_p50": spec.output_min * 0.5 ** (-1 / spec.output_alpha),
        "output_p99": spec.output_min * 0.01 ** (-1 / spec.output_alpha),
    }


def generate_trace(spec: TraceSpec) -> Trace:
    """Materialize the arrival stream: a non-homogeneous Poisson
    process (thinning against ``rate_at``) with per-arrival length /
    session / tenant draws in a FIXED order from ONE rng, so the whole
    trace is a pure function of ``spec.seed``."""
    rng = np.random.default_rng(spec.seed)
    # group prefixes + session->group assignment are drawn first so
    # they are independent of trace length
    prefixes = rng.integers(0, spec.vocab,
                            size=(spec.prefix_groups, spec.prefix_len))
    session_group = rng.integers(0, spec.prefix_groups,
                                 size=spec.sessions)
    shares = np.array([ten[1] for ten in spec.tenants], float)
    cum = np.cumsum(shares / shares.sum())
    peak = peak_rate(spec)
    arrivals = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= spec.duration_s:
            break
        u = float(rng.random())  # thinning draw — consumed always
        if u * peak >= rate_at(spec, t):
            continue
        plen = int(np.clip(
            round(spec.prompt_median
                  * math.exp(float(rng.normal(0.0, spec.prompt_sigma)))),
            spec.prompt_min, spec.prompt_max))
        nnew = int(np.clip(
            round(spec.output_min * (1.0 + float(rng.pareto(
                spec.output_alpha)))),
            spec.output_min, spec.output_max))
        sess = int((int(rng.zipf(spec.session_zipf)) - 1) % spec.sessions)
        ti = int(np.searchsorted(cum, float(rng.random()),
                                 side="right"))
        ti = min(ti, len(spec.tenants) - 1)
        ten = spec.tenants[ti]
        if len(ten) == 4 and ten[3] == "prefill_heavy":
            # heavy-class REDRAW: two extra rng values consumed only
            # on heavy arrivals, so a spec without heavy tenants
            # replays byte-identically under the same seed
            plen = int(np.clip(
                round(spec.heavy_prompt_median * math.exp(float(
                    rng.normal(0.0, spec.heavy_prompt_sigma)))),
                spec.prompt_min, spec.prompt_max))
            nnew = int(np.clip(
                round(spec.output_min * (1.0 + float(rng.pareto(
                    spec.output_alpha)))),
                spec.output_min, spec.heavy_output_max))
        tail = rng.integers(0, spec.vocab,
                            size=plen - spec.prefix_len)
        prompt = np.concatenate(
            [prefixes[int(session_group[sess])], tail]).astype(np.int32)
        name, prio = ten[0], ten[2]
        arrivals.append(Arrival(t=t, prompt=prompt, max_new=nnew,
                                session=f"s{sess}", tenant=str(name),
                                priority=int(prio)))
    return Trace(spec=spec, arrivals=tuple(arrivals))


# ---------------------------------------------------------------------
# chaos schedule: wall-clock faults phase-aligned to the load curve
# ---------------------------------------------------------------------


class ChaosSchedule:
    """Wall-clock chaos phases in TRACE time.  One schedule owns the
    sim clock: ``replay`` anchors it at t=0 of the trace, the
    ``ChaosTransport`` built by :meth:`chaos_transport` reads the same
    clock for its fault ``windows``, and :meth:`poll` fires registered
    ``kill()``s when their trace time comes — so "kill a replica
    mid-flash-crowd" is literally a timestamp inside the crowd window.

    Args:
      windows: ``[(t_start, t_end, kinds)]`` transport-fault phases in
        trace seconds (validated here, handed to ``ChaosTransport``).
      kills: ``[(t, target)]`` — at trace time ``t`` call the zero-arg
        function registered for ``target`` (``register_kill``), once.
      time_scale: wall seconds per trace second (match ``replay``'s).
    """

    def __init__(self, *, windows=(), kills=(),
                 time_scale: float = 1.0):
        self.windows = _validate_windows(windows)
        self.kills = tuple(sorted(
            (float(t), str(name)) for t, name in kills))
        if any(t < 0 for t, _ in self.kills):
            raise ValueError("kill times must be >= 0")
        self.time_scale = float(time_scale)
        self._kill_fns: dict[str, Callable[[], None]] = {}
        self._fired: set[int] = set()
        self._t0: Optional[float] = None

    def register_kill(self, name: str,
                      fn: Callable[[], None]) -> None:
        self._kill_fns[str(name)] = fn

    def start(self, t0: Optional[float] = None) -> "ChaosSchedule":
        """Anchor trace t=0 at ``t0`` (a ``telemetry.now()`` stamp;
        default: now).  ``replay`` calls this with its own anchor so
        windows and kills share the pacing loop's clock."""
        self._t0 = telemetry.now() if t0 is None else float(t0)
        return self

    def clock(self) -> float:
        """Current trace time (0.0 before :meth:`start`)."""
        if self._t0 is None:
            return 0.0
        return (telemetry.now() - self._t0) / self.time_scale

    def chaos_transport(self, seed: int = 0, **kw) -> ChaosTransport:
        """A ``ChaosTransport`` whose wall-clock fault windows run on
        THIS schedule's trace clock (plus any op-counter schedule
        passed through ``kw``)."""
        return ChaosTransport(seed, windows=self.windows,
                              clock=self.clock, **kw)

    def poll(self) -> list[str]:
        """Fire every kill whose trace time has arrived (once each);
        returns the targets fired this call.  An unregistered target
        raises — a drill with a missing kill hook is a bug, not a
        no-op."""
        t = self.clock()
        fired = []
        for i, (kt, name) in enumerate(self.kills):
            if i in self._fired or t < kt:
                continue
            self._fired.add(i)
            fn = self._kill_fns.get(name)
            if fn is None:
                raise KeyError(
                    f"kill target {name!r} was never registered")
            telemetry.metrics().counter("sim_kills_total",
                                        target=name).inc()
            flight_recorder.record("sim_kill", target=name, sim_t=kt)
            fn()
            fired.append(name)
        return fired


class ReplicaPool:
    """Pre-warmed spare replicas behind ``Autoscaler`` verbs.  A real
    spawn pays replica construction + weight warm; the drill pays that
    cost up front (spares are built before the trace starts) so
    ``spawn_replica`` measures the *control loop's* convergence, not
    JIT warmup.  LIFO drain returns the most recently spawned."""

    def __init__(self, gateway, spares: Sequence = ()):
        self.gateway = gateway
        self._spares = list(spares)
        self._spawned: list[str] = []

    def spawn_replica(self) -> str:
        if not self._spares:
            raise RuntimeError("replica pool exhausted (no spares)")
        rep = self._spares.pop()
        self.gateway.add_replica(rep)
        self._spawned.append(rep.name)
        return rep.name

    def drain_replica(self) -> str:
        if not self._spawned:
            raise RuntimeError("no pool-spawned replica to drain")
        name = self._spawned.pop()
        self.gateway.remove_replica(name)
        return name

    def replica_count(self) -> int:
        return self.gateway.alive_replicas()

    def spares_left(self) -> int:
        return len(self._spares)


# ---------------------------------------------------------------------
# replay: pace a trace against a gateway
# ---------------------------------------------------------------------


def _percentile(xs: list, q: float) -> Optional[float]:
    if not xs:
        return None
    return float(np.percentile(np.asarray(xs, float), q))


def replay(trace: Trace, gateway, *, time_scale: float = 1.0,
           schedule: Optional[ChaosSchedule] = None,
           slo_ttft_s: Optional[float] = None,
           on_tick: Optional[Callable[[float], None]] = None,
           tick_interval_s: float = 0.1,
           drain_timeout_s: float = 60.0,
           label: str = "replay") -> dict:
    """Replay ``trace`` against ``gateway`` in (scaled) wall time.

    One single-threaded pacing loop: sleep to each arrival's wall
    deadline, submit it, and between submissions poll completed
    results (``gateway.try_result`` — non-blocking, so a slow request
    never stalls the offered load), fire due chaos kills
    (``schedule.poll``), and call ``on_tick(sim_t)`` roughly every
    ``tick_interval_s`` wall seconds (the drill's autoscaler tick).
    After the last arrival the loop drains until every request has a
    result or ``drain_timeout_s`` passes.

    TTFT is measured on the simulator's clock — first token time minus
    the wall moment THIS loop submitted — so gateway queueing and
    failover retries count against the SLO, exactly as a user would
    experience them.

    Returns a report: offered/completed/error/duplicate counts, SLO
    attainment (completed-ok-within-TTFT / arrivals), ttft p50/p95,
    the wall duration, and the raw per-request results.
    """
    spec = trace.spec
    m = telemetry.metrics()
    t0 = telemetry.now()
    if schedule is not None:
        schedule.start(t0)
    pending: dict = {}         # rid -> (arrival, wall submit stamp)
    results: list[dict] = []
    seen_rids: set = set()
    duplicates = errors = slo_miss = ok_within = 0
    next_tick = t0
    phase = "base"
    flight_recorder.record("sim_phase", phase=phase, sim_t=0.0)

    def service():
        """One poll round: results, kills, tick.  Never blocks."""
        nonlocal next_tick, duplicates, errors, slo_miss, ok_within
        if schedule is not None:
            schedule.poll()
        for rid in list(pending):
            res = gateway.try_result(rid)
            if res is None:
                continue
            arrival, t_sub = pending.pop(rid)
            if rid in seen_rids:
                duplicates += 1
                m.counter("sim_duplicate_results_total").inc()
            seen_rids.add(rid)
            m.counter("sim_results_total").inc()
            t_first = res.get("t_first")
            ttft = None if t_first is None else t_first - t_sub
            res = dict(res, sim_t=arrival.t, sim_ttft=ttft,
                       tenant=arrival.tenant)
            results.append(res)
            if res.get("error") is not None:
                errors += 1
            elif (slo_ttft_s is not None
                  and (ttft is None or ttft > slo_ttft_s)):
                slo_miss += 1
                m.counter("sim_slo_miss_total").inc()
            else:
                ok_within += 1
        nw = telemetry.now()
        if on_tick is not None and nw >= next_tick:
            next_tick = nw + tick_interval_s
            on_tick((nw - t0) / time_scale)

    with telemetry.span("sim_replay", label=label,
                        arrivals=len(trace.arrivals)):
        for a in trace.arrivals:
            target = t0 + a.t * time_scale
            while True:
                nw = telemetry.now()
                if nw >= target:
                    break
                service()
                _sleep(min(target - telemetry.now(), 0.005))
            ph = "crowd" if in_crowd(spec, a.t) else "base"
            if ph != phase:
                phase = ph
                flight_recorder.record("sim_phase", phase=ph,
                                       sim_t=a.t)
            m.gauge("sim_offered_qps").set(rate_at(spec, a.t))
            rid = gateway.submit(a.prompt, max_new_tokens=a.max_new,
                                 session=a.session, tenant=a.tenant,
                                 priority=a.priority)
            m.counter("sim_arrivals_total", tenant=a.tenant).inc()
            pending[rid] = (a, telemetry.now())
        deadline = telemetry.now() + drain_timeout_s
        while pending and telemetry.now() < deadline:
            service()
            _sleep(0.002)
        service()  # a final poll so the last tick/kill lands
    wall_s = telemetry.now() - t0
    ttfts = [r["sim_ttft"] for r in results
             if r["sim_ttft"] is not None and r.get("error") is None]
    n = len(trace.arrivals)
    return {
        "arrivals": n,
        "completed": len(results),
        "undrained": len(pending),
        "errors": errors,
        "duplicates": duplicates,
        "slo_miss": slo_miss,
        "slo_attainment": (ok_within / n) if n else 1.0,
        "offered_qps": (n / (spec.duration_s * time_scale)
                        if spec.duration_s else 0.0),
        "ttft_p50_s": _percentile(ttfts, 50.0),
        "ttft_p95_s": _percentile(ttfts, 95.0),
        "wall_s": wall_s,
        "results": results,
    }


# ---------------------------------------------------------------------
# capacity: stepped-rate search + fitted model
# ---------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CapacityPoint:
    """One configuration's measured sustainable point."""

    config: Mapping
    qps: float
    attainment: float
    ttft_p95_s: Optional[float]


def stepped_rate_search(gateway, base_spec: TraceSpec, *,
                        slo_ttft_s: float,
                        attainment: float = 0.9,
                        ladder: Sequence[float] = (4, 8, 16, 32, 64,
                                                   128, 256),
                        min_arrivals: int = 16,
                        max_segment_s: float = 3.0,
                        time_scale: float = 1.0,
                        drain_timeout_s: float = 15.0,
                        config: Optional[Mapping] = None) -> dict:
    """Find the configuration's sustainable QPS at the TTFT SLO by
    walking a geometric rate ladder: each rung replays a flat-rate
    segment of ``base_spec``'s request mix and must keep error-free
    SLO attainment at or above ``attainment``; the first failing rung
    stops the walk and the previous rung is the sustainable rate.
    Segment length adapts (``min_arrivals`` at low rates, capped at
    ``max_segment_s``) so every rung sees a meaningful sample.

    Returns ``{"sustainable_qps", "point": CapacityPoint, "rungs",
    "capped"}`` — ``capped`` True when even the top rung passed (the
    ladder, not the system, was the limit).  The sustainable rate also
    lands on the ``sim_capacity_qps{**config}`` gauge.
    """
    rungs = []
    best: Optional[CapacityPoint] = None
    cfg = dict(config or {})
    for i, q in enumerate(ladder):
        seg = min(max(min_arrivals / q, 0.5), max_segment_s)
        spec = dataclasses.replace(
            base_spec, mean_qps=float(q), duration_s=seg,
            diurnal_amplitude=0.0, flash_crowds=(),
            seed=base_spec.seed + 1000 + i)
        rep = replay(generate_trace(spec), gateway,
                     time_scale=time_scale, slo_ttft_s=slo_ttft_s,
                     drain_timeout_s=drain_timeout_s,
                     label=f"capacity:q{q}")
        ok = (rep["slo_attainment"] >= attainment
              and rep["errors"] == 0 and rep["undrained"] == 0)
        rungs.append({"qps": float(q), "ok": ok,
                      "attainment": rep["slo_attainment"],
                      "ttft_p95_s": rep["ttft_p95_s"],
                      "arrivals": rep["arrivals"]})
        if not ok:
            break
        best = CapacityPoint(config=cfg, qps=float(q),
                             attainment=rep["slo_attainment"],
                             ttft_p95_s=rep["ttft_p95_s"])
    sustainable = best.qps if best is not None else 0.0
    telemetry.metrics().gauge(
        "sim_capacity_qps",
        **{k: str(v) for k, v in cfg.items()}).set(sustainable)
    return {"sustainable_qps": sustainable, "point": best,
            "rungs": rungs, "capped": bool(rungs) and rungs[-1]["ok"]}


class CapacityModel:
    """Sustainable QPS as a function of replica count, fitted from
    measured ``CapacityPoint``s (configs must carry ``"replicas"``).
    Two or more distinct replica counts fit a line (least squares);
    one point scales proportionally through the origin — the
    conservative single-point model."""

    def __init__(self, points: Sequence[CapacityPoint]):
        if not points:
            raise ValueError("CapacityModel needs >= 1 point")
        self.points = tuple(points)
        ns = np.array([float(p.config["replicas"]) for p in points])
        qs = np.array([p.qps for p in points])
        if len(set(ns.tolist())) >= 2:
            self._slope, self._intercept = np.polyfit(ns, qs, 1)
        else:
            self._slope = float(qs[0] / max(ns[0], 1.0))
            self._intercept = 0.0

    def capacity(self, replicas: int) -> float:
        """Predicted sustainable QPS with ``replicas`` replicas."""
        return float(self._slope * replicas + self._intercept)

    def required(self, qps: float, *, headroom: float = 1.0,
                 max_replicas: int = 64) -> int:
        """Smallest replica count whose predicted capacity covers
        ``qps * headroom`` (at least 1; capped at ``max_replicas``)."""
        need = qps * headroom
        for n in range(1, max_replicas + 1):
            if self.capacity(n) >= need:
                return n
        return max_replicas

    def describe(self) -> dict:
        return {"slope": float(self._slope),
                "intercept": float(self._intercept),
                "points": [{"config": dict(p.config), "qps": p.qps,
                            "attainment": p.attainment,
                            "ttft_p95_s": p.ttft_p95_s}
                           for p in self.points]}


# ---------------------------------------------------------------------
# closed-loop drill
# ---------------------------------------------------------------------


def run_drill(trace: Trace, gateway, autoscaler, model: CapacityModel,
              *, schedule: Optional[ChaosSchedule] = None,
              time_scale: float = 1.0, headroom: float = 1.0,
              slo_ttft_s: Optional[float] = None,
              tick_interval_s: float = 0.25,
              max_replicas: int = 8,
              drain_timeout_s: float = 60.0) -> dict:
    """The closed-loop acceptance scenario: replay ``trace`` while the
    ``Autoscaler`` (stepped from the pacing loop, one tick per
    ``tick_interval_s``) must hold live capacity at the fitted model's
    ``required(rate_at(t))`` as the curve moves — through the flash
    crowd AND through whatever ``schedule`` kills mid-crowd.

    Convergence accounting: whenever ``gateway.alive_replicas()``
    drops below the target the drill opens a deficit episode; when
    capacity catches back up the episode closes and its wall duration
    accrues to ``sim_drill_convergence_seconds_total`` (one
    ``drill_converged`` flight event each).  SLO-violation seconds
    accrue on the watchdog's ``slo_violation_seconds_total`` as its
    evaluations tick.  Both are per-second-gateable via
    ``perf_regress.from_registry``.

    Returns ``{"replay", "episodes", "converged", "samples"}`` —
    ``converged`` is True when every deficit episode closed before the
    trace ended.
    """
    m = telemetry.metrics()
    samples: list[dict] = []
    episodes: list[dict] = []
    open_since: list = [None, 0]  # [wall stamp, target at open]

    def on_tick(sim_t: float) -> None:
        # observe BEFORE acting: step() may heal a deficit (post-kill
        # spawn) within this very tick, and the episode must still be
        # seen open for at least one observation
        target = min(model.required(rate_at(trace.spec, sim_t),
                                    headroom=headroom), max_replicas)
        actual = gateway.alive_replicas()
        autoscaler.step()
        nw = telemetry.now()
        if actual < target and open_since[0] is None:
            open_since[0], open_since[1] = nw, target
        elif actual >= target and open_since[0] is not None:
            dur = nw - open_since[0]
            episodes.append({"seconds": dur, "sim_t": sim_t,
                             "target": open_since[1],
                             "closed": True})
            m.counter("sim_drill_convergence_seconds_total").inc(dur)
            flight_recorder.record("drill_converged", sim_t=sim_t,
                                   seconds=dur, target=open_since[1],
                                   actual=actual)
            open_since[0] = None
        samples.append({"sim_t": sim_t, "target": target,
                        "actual": actual,
                        "state": autoscaler.watchdog.state})

    rep = replay(trace, gateway, time_scale=time_scale,
                 schedule=schedule, slo_ttft_s=slo_ttft_s,
                 on_tick=on_tick, tick_interval_s=tick_interval_s,
                 drain_timeout_s=drain_timeout_s, label="drill")
    if open_since[0] is not None:
        dur = telemetry.now() - open_since[0]
        episodes.append({"seconds": dur, "sim_t": None,
                         "target": open_since[1], "closed": False})
        m.counter("sim_drill_convergence_seconds_total").inc(dur)
    converged = all(e["closed"] for e in episodes)
    return {"replay": rep, "episodes": episodes,
            "converged": converged, "samples": samples}


def _sleep(seconds: float) -> None:
    if seconds > 0:
        time.sleep(seconds)

"""Checkpoint / resume (SURVEY.md §5: the reference has NONE — the
trained model lives in the PS thread's memory and dies with the driver).

Format: one msgpack file (flax canonical encoding) holding the training
pytrees plus a JSON-encoded cursor (epoch / round / step).  Typed PRNG
keys are packed to their raw uint32 data on save and re-wrapped on load
(msgpack cannot carry extended dtypes).  Writes are atomic
(tmp + rename), so a checkpoint is never observed half-written.

Trainers integrate via ``Trainer(..., checkpoint_dir=...)`` to save at
every epoch boundary (and optionally every N commit rounds), and
``train(..., resume_from=...)`` to continue a killed run; the resumed
run reproduces the uninterrupted one bit-for-bit because every source of
randomness (data shuffle, commit permutations, dropout rngs) is keyed by
saved state.
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any, Mapping

import jax
import jax.numpy as jnp
from flax import serialization as flax_serialization

Pytree = Any

LATEST = "ckpt_latest.msgpack"


def _is_key(x) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key)


def pack_prng_keys(tree: Pytree) -> Pytree:
    """Typed PRNG key leaves -> raw uint32 key data (serializable)."""
    return jax.tree_util.tree_map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree)


def unpack_prng_keys(template: Pytree, tree: Pytree) -> Pytree:
    """Re-wrap raw key data wherever ``template`` holds a typed key."""
    return jax.tree_util.tree_map(
        lambda t, x: jax.random.wrap_key_data(jnp.asarray(x))
        if _is_key(t) else x, template, tree)


def save_checkpoint(path: str | os.PathLike, state: Pytree,
                    cursor: Mapping[str, Any]) -> str:
    """Atomically write ``{state, cursor}``; returns the file path.

    ``path`` may be a directory — created if needed, file named
    ``ckpt_latest.msgpack`` — or an explicit ``.msgpack``/``.ckpt`` file
    path.  Any other path (including dotted directory names like
    ``runs/v1.5``) is treated as a directory, matching
    ``load_checkpoint``'s ``is_dir`` check once it exists.
    """
    path = pathlib.Path(path)
    if path.suffix in (".msgpack", ".ckpt") and not path.is_dir():
        path.parent.mkdir(parents=True, exist_ok=True)
    else:
        path.mkdir(parents=True, exist_ok=True)
        path = path / LATEST
    payload = {
        "state": pack_prng_keys(jax.device_get(state)),
        "cursor": json.dumps(dict(cursor)),
    }
    data = flax_serialization.to_bytes(payload)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)
    return str(path)


def save_ps_snapshot(path: str | os.PathLike, snapshot: Pytree) -> str:
    """Atomic free-form msgpack write for ``HostParameterServer``
    warm-restart snapshots (tmp + rename, same crash-safety contract as
    ``save_checkpoint``).  Unlike the trainer checkpoints, a snapshot
    is restored WITHOUT a template (the restarting server has none —
    its state died with the old process), so this rides flax's
    self-describing ``msgpack_serialize`` encoding."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    data = flax_serialization.msgpack_serialize(
        jax.device_get(snapshot))
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)
    return str(path)


def load_ps_snapshot(path: str | os.PathLike) -> Pytree:
    """Inverse of ``save_ps_snapshot`` — no template needed."""
    return flax_serialization.msgpack_restore(
        pathlib.Path(path).read_bytes())


def ps_snapshot_info(path: str | os.PathLike) -> dict:
    """Operational peek at a PS snapshot file: which server class
    wrote it and how far it got.  Returns ``{"sharded": K or None,
    "num_commits": int, "workers_cached": int, "epoch": int}`` —
    ``sharded`` drives ``PSServer.restart_from``'s dispatch (an
    unsharded ``HostParameterServer`` snapshot has no ``"sharded"``
    key; a ``ShardedParameterServer`` snapshot carries the shard count
    plus per-shard clock/dedupe sections).  ``epoch`` is the
    replication fencing epoch the snapshot was taken under (0 when the
    server was never part of a replica group, or predates replication)
    — the postmortem uses it to place a snapshot on the failover
    timeline.  ``last_acked`` maps worker id (str) → highest commit
    seq the snapshot proves acknowledged — the postmortem's
    cross-check key against the flight recorder (on a sharded snapshot
    that is the MIN across shards: a logical commit is acked only once
    its last shard replied)."""
    snap = load_ps_snapshot(path)
    epoch = int(snap.get("epoch", 0))
    if "sharded" in snap:
        shards = snap["shards"]
        acked: dict[str, int] = {}
        for s in shards:
            for w, e in s["last_reply"].items():
                seq = int(e["seq"])
                acked[w] = min(acked.get(w, seq), seq)
        return {
            "sharded": int(snap["sharded"]),
            "num_commits": int(shards[0]["num_commits"]),
            "workers_cached": len({w for s in shards
                                   for w in s["last_reply"]}),
            "last_acked": acked,
            "epoch": epoch,
        }
    return {
        "sharded": None,
        "num_commits": int(snap["num_commits"]),
        "workers_cached": len(snap["last_reply"]),
        "last_acked": {w: int(e["seq"])
                       for w, e in snap["last_reply"].items()},
        "epoch": epoch,
    }


def ps_snapshot_center(snapshot: dict | str | os.PathLike) -> Pytree:
    """The center parameter tree of a PS snapshot (dict or file) —
    both the unsharded and the sharded formats store the assembled
    ``"center"`` at the top level.  This is the serving side's entry
    point: ``ServingGateway.rolling_update(path)`` resolves its new
    weights through here, connecting the training half of the repo
    (PS snapshots) to the serving half (hot weight swaps) without
    needing the rule, clocks, or dedupe state a full
    ``from_snapshot`` restore would."""
    if isinstance(snapshot, (str, os.PathLike)):
        snapshot = load_ps_snapshot(snapshot)
    if "center" not in snapshot:
        raise ValueError(
            "not a PS snapshot: no 'center' key (expected a file "
            "written by save_ps_snapshot / HostParameterServer."
            "save_snapshot / ShardedParameterServer.save_snapshot)")
    return snapshot["center"]


SHARDED = "ckpt_sharded"
_POINTER = "LATEST"


def _sharded_latest(root: pathlib.Path) -> str | None:
    pointer = root / _POINTER
    if not pointer.exists():
        return None
    tag = pointer.read_text().strip()
    return tag if (root / tag).exists() else None


def has_sharded(path: str | os.PathLike) -> bool:
    """True if ``path`` holds a complete sharded (orbax) checkpoint."""
    return _sharded_latest(pathlib.Path(path) / SHARDED) is not None


def save_sharded(path: str | os.PathLike, state: Pytree,
                 cursor: Mapping[str, Any]) -> str:
    """Sharded / multi-host checkpoint via orbax.

    Unlike ``save_checkpoint`` (which fetches the whole state to one
    host), every process writes only its own array shards, so this
    works for tensor-parallel or otherwise non-fully-addressable
    state spanning hosts.  All processes must call it (orbax
    coordinates via the jax.distributed client).  Restore with
    ``load_sharded`` against an identically-sharded template.

    Crash-safe like the msgpack path: each save point writes to its
    own cursor-derived directory and only then atomically updates a
    ``LATEST`` pointer, so a kill mid-save always leaves the previous
    checkpoint loadable and never a state/cursor mismatch.  Older save
    points are pruned after the pointer moves.
    """
    import orbax.checkpoint as ocp

    root = pathlib.Path(path).resolve() / SHARDED
    parts = ["".join(c for c in f"{k}{cursor[k]}"
                     if c.isalnum() or c in "-.")
             for k in sorted(cursor)
             if isinstance(cursor[k], (int, float, str))]
    tag = "state_" + ("_".join(parts) if parts else "0")
    ckptr = ocp.StandardCheckpointer()
    # force only clears a half-written attempt at THIS tag (a prior
    # crash); completed older tags stay untouched until the pointer
    # moves past them.
    ckptr.save(root / tag, pack_prng_keys(state), force=True)
    ckptr.wait_until_finished()
    if jax.process_index() == 0:
        tmp = root / (tag + ".cursor.tmp")
        tmp.write_text(json.dumps(dict(cursor)))
        os.replace(tmp, root / (tag + ".cursor.json"))
        tmp = root / (_POINTER + ".tmp")
        tmp.write_text(tag)
        os.replace(tmp, root / _POINTER)
        for old in root.iterdir():  # prune superseded save points
            if (old.name.startswith("state_") and old.is_dir()
                    and old.name != tag):
                import shutil

                shutil.rmtree(old, ignore_errors=True)
                (root / (old.name + ".cursor.json")).unlink(
                    missing_ok=True)
    return str(root)


def load_sharded(path: str | os.PathLike, state_template: Pytree
                 ) -> tuple[Pytree, dict]:
    """Restore a ``save_sharded`` checkpoint INTO the template's
    shardings: ``state_template`` is a pytree of (sharded) arrays — or
    ``jax.ShapeDtypeStruct``s with ``.sharding`` — matching the saved
    structure; each process reads only the shards it owns."""
    import orbax.checkpoint as ocp

    root = pathlib.Path(path).resolve() / SHARDED
    tag = _sharded_latest(root)
    if tag is None:
        raise FileNotFoundError(
            f"no complete sharded checkpoint under {root}")
    packed = pack_prng_keys(state_template)
    abstract = jax.tree_util.tree_map(
        lambda v: v if isinstance(v, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(v.shape, v.dtype,
                                  sharding=getattr(v, "sharding",
                                                   None)),
        packed)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(root / tag, abstract)
    state = unpack_prng_keys(state_template, restored)
    cursor = json.loads((root / (tag + ".cursor.json")).read_text())
    return state, cursor


def load_checkpoint(path: str | os.PathLike, state_template: Pytree
                    ) -> tuple[Pytree, dict]:
    """Read a checkpoint written by ``save_checkpoint``.

    ``state_template`` must be a pytree of the same structure/shapes as
    the saved state (trainers construct it for free by building their
    initial states before resuming).  Returns ``(state, cursor)``.
    """
    path = pathlib.Path(path)
    if path.is_dir():
        path = path / LATEST
    template = {
        "state": pack_prng_keys(state_template),
        "cursor": "",
    }
    payload = flax_serialization.from_bytes(template,
                                            path.read_bytes())
    state = unpack_prng_keys(state_template, payload["state"])
    return state, json.loads(payload["cursor"])

"""Round attribution: XLA cost extraction + roofline math for the mesh
data plane (ROADMAP item 1 — "where do a round's milliseconds go?").

Three small, dependency-light layers shared by ``MeshDataplane`` (the
cost ledger), ``MeshRoundDriver`` (the sampled step-time decomposition),
``bench.py`` and ``scripts/perf_attrib.py``:

* :func:`extract_cost` — version-tolerant read of
  ``Compiled.cost_analysis()`` / ``memory_analysis()`` for an AOT
  executable.  On jax 0.4.x ``cost_analysis()`` returns a list with one
  dict per executable and ``'flops'`` counts PER-DEVICE flops of the
  SPMD program (verified empirically for the shard_map round); absent
  or malformed analyses degrade to ``None`` fields, never raise.
* :func:`roofline` — two-term roofline: compute time against a peak
  FLOP/s and communication time against a peak byte/s, classified
  compute- vs comm-bound by arithmetic intensity.  Pure math, unit
  tested against hand-computed numbers.
* :func:`mfu` / :func:`attrib_overhead` — observed-MFU accounting and
  the ``telemetry_overhead``-style microbench bounding the driver's
  disabled-path sampling guard (PERF.md no-op budget).
"""

from __future__ import annotations

import time
from typing import Any

__all__ = [
    "extract_cost",
    "roofline",
    "mfu",
    "attrib_overhead",
]


def extract_cost(compiled: Any) -> dict:
    """Pull {flops, bytes_accessed, peak_temp_bytes, output_bytes,
    argument_bytes, generated_code_bytes} off an AOT ``Compiled``.

    Every field is ``None`` when the backend does not expose it (the
    ledger stays honest instead of guessing); ``flops`` is the
    per-device figure XLA reports for the SPMD partition.
    """
    out: dict[str, Any] = {
        "flops": None,
        "bytes_accessed": None,
        "peak_temp_bytes": None,
        "output_bytes": None,
        "argument_bytes": None,
        "generated_code_bytes": None,
    }
    try:
        cost = compiled.cost_analysis()
    except Exception:
        cost = None
    if cost:
        # jax 0.4.x: list of one dict per executable; newer jax may
        # hand back the dict directly.
        rec = cost[0] if isinstance(cost, (list, tuple)) else cost
        if isinstance(rec, dict):
            flops = rec.get("flops")
            if flops is not None and flops >= 0:
                out["flops"] = float(flops)
            nbytes = rec.get("bytes accessed")
            if nbytes is not None and nbytes >= 0:
                out["bytes_accessed"] = float(nbytes)
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        for field, attr in (
                ("peak_temp_bytes", "temp_size_in_bytes"),
                ("output_bytes", "output_size_in_bytes"),
                ("argument_bytes", "argument_size_in_bytes"),
                ("generated_code_bytes", "generated_code_size_in_bytes")):
            val = getattr(mem, attr, None)
            if val is not None and val >= 0:
                out[field] = int(val)
    return out


def roofline(flops: float, comm_bytes: float, peak_flops: float,
             peak_bytes_per_sec: float) -> dict:
    """Two-term roofline for one device's share of a round.

    ``t_compute = flops / peak_flops``; ``t_comm = comm_bytes /
    peak_bytes_per_sec``; the predicted round floor is whichever
    dominates, and ``bound`` names it.  ``arithmetic_intensity`` is
    flops per communicated byte — above the machine balance point
    (``peak_flops / peak_bytes_per_sec``) the round is compute-bound.
    Degenerate peaks (zero/NaN) yield a zeroed record rather than a
    division error so unknown devices stay representable.
    """
    flops = max(float(flops or 0.0), 0.0)
    comm_bytes = max(float(comm_bytes or 0.0), 0.0)

    def _finite(x):
        x = float(x or 0.0)
        return x if x > 0.0 and x == x else 0.0

    pf = _finite(peak_flops)
    pb = _finite(peak_bytes_per_sec)
    t_compute = flops / pf if pf else 0.0
    t_comm = comm_bytes / pb if pb else 0.0
    t_roofline = max(t_compute, t_comm)
    intensity = flops / comm_bytes if comm_bytes else float("inf")
    return {
        "t_compute_s": t_compute,
        "t_comm_s": t_comm,
        "t_roofline_s": t_roofline,
        "bound": "compute" if t_compute >= t_comm else "comm",
        "arithmetic_intensity": intensity,
        "machine_balance": (pf / pb) if pb else float("inf"),
    }


def mfu(flops: float, seconds: float, peak_flops: float,
        n_chips: int = 1) -> float | None:
    """Observed model-FLOPs utilization: ``flops`` executed in
    ``seconds`` against ``n_chips x peak_flops``.  ``None`` when any
    term is degenerate (zero time, unknown/NaN peak) — callers must
    null the figure, not fabricate it.
    """
    try:
        flops = float(flops)
        seconds = float(seconds)
        peak_flops = float(peak_flops)
    except (TypeError, ValueError):
        return None
    if (flops <= 0 or seconds <= 0 or n_chips <= 0
            or not peak_flops > 0):  # NaN-safe
        return None
    return flops / seconds / (peak_flops * n_chips)


def attrib_overhead(n: int = 200_000) -> dict:
    """Per-round cost (ns) of the driver's attribution guard when
    sampling is OFF — the exact branch every un-instrumented
    ``MeshRoundDriver.dispatch`` pays (PERF.md no-op budget, measured
    the same way as ``profiling.telemetry_overhead``).

    ``disabled_ns`` is ``attrib_every=0`` (the default: one int test);
    ``armed_unsampled_ns`` is ``attrib_every=N`` on a non-sampled round
    (the guard's modulo plus the end-of-dispatch host-gap clock stamp).
    Both run against the real ``MeshRoundDriver._attrib_tick`` so a
    refactor cannot quietly grow the fast path without this number
    moving.
    """
    from types import SimpleNamespace

    from distkeras_tpu.parallel.ps_dataplane import MeshRoundDriver

    tick = MeshRoundDriver._attrib_tick

    def per_call_ns(fn) -> float:
        fn()
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n * 1e9

    off = SimpleNamespace(attrib_every=0, _round_index=0, _last_end=None)
    armed = SimpleNamespace(attrib_every=7, _round_index=1,
                            _last_end=time.perf_counter())

    def off_op():
        off._round_index += 1
        tick(off)

    def armed_op():
        # stay off the sampled residue so only the guard is timed
        armed._round_index += 1
        if armed._round_index % 7 == 0:
            armed._round_index += 1
        tick(armed)
        armed._last_end = time.perf_counter()

    return {
        "disabled_ns": round(per_call_ns(off_op), 1),
        "armed_unsampled_ns": round(per_call_ns(armed_op), 1),
    }

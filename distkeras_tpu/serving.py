"""Continuous-batching decode engine — slot-based LM serving over a
persistent KV cache.

``models.generate`` and ``StreamingGenerator`` are run-to-completion
servers: a micro-batch enters the compiled scan together and leaves
together, so an ``eos``-finished row keeps burning full T=1 steps until
its whole batch drains, and every step pays for the STATIC cache
envelope regardless of the live prefix (the §18 cost law).  Under mixed
prompt/output-length traffic most of the measured decode bandwidth is
spent on drained rows and oversized envelopes.

``DecodeEngine`` is the iteration-level scheduler that fixes both — the
Orca (Yu et al., OSDI '22) / vLLM (Kwon et al., SOSP '23) architecture
adapted to XLA's static-shape world:

* a persistent ``[slots, ...]`` KV-cache POOL lives on device across
  requests, one pool per ``max_len`` BUCKET (e.g. 512/1024/2048
  envelopes), so a short request never pays a long request's static
  cache;
* one compiled STEP program per bucket advances every live slot by one
  token (``slot_pos`` per-row cache positions; per-slot eos /
  remaining-token state rides along), ``steps_per_sync`` steps per
  host round-trip;
* one compiled PREFILL program per (bucket, padded prompt length)
  writes an admitted request's prompt into a free slot via
  ``dynamic_update_slice`` — prompts are right-padded to
  ``prefill_align`` so arbitrary lengths hit a bounded set of
  compiled shapes, and the padded rows' K/V are masked by the per-slot
  causal horizon and overwritten by the first generated tokens;
* finished rows are evicted and replaced BETWEEN steps, so steady-state
  serving keeps every slot live and compiles nothing new — ragged
  arrivals reuse the same bounded program set (asserted by
  ``compile_counts`` and the tier-1 compile guard).

Greedy results are bit-identical to ``models.generate`` per request and
independent of admission order (each slot's attention reads only its
own cache rows).  Sampling draws from the engine's step/prefill key
stream, so it is reproducible for a fixed seed and arrival order but
NOT admission-order invariant.

Graceful degradation (the fault-tolerance layer, docs/API.md "Fault
tolerance"): ``queue_bound`` turns the admission queue into Orca-style
load shedding (``submit`` raises ``ShedError`` + counts
``serving_shed_total{reason}`` at the bound); per-request ``deadline``s
expire queued AND live requests into ``error`` results instead of
holding capacity; a poisoned request (failing prefill) errors out
alone (``error`` result key, ``serving_request_errors_total``) without
killing ``step()`` for its slot neighbors; ``drain()`` finishes the
backlog and ``close()`` cancels what remains (every in-flight id comes
back, ``error="engine_closed"``) and releases the device pools.

Prefill reuse + scheduling (ISSUE 8, the two standard fixes for the
remaining hot-path waste):

* ``prefix_cache_bytes`` turns on a SHARED-PREFIX KV CACHE — a
  host-side longest-prefix trie over token ids at ``prefill_align``
  granularity (SGLang's RadixAttention idea, Zheng et al. 2024) whose
  nodes hold ref-counted DEVICE segments (``[1, KVH, align, D]`` per
  cache leaf, envelope-free so one store serves every bucket).  On
  admit, the longest cached prefix is copied device-to-device into
  the slot (``dynamic_update_slice``, zero model FLOPs) and only the
  uncached tail is prefilled; finished requests donate their aligned
  prompt blocks back, LRU-evicted beyond the byte budget with live
  refs pinned.  ``swap_variables`` INVALIDATES the store — cached KV
  under new weights is silently wrong.
* ``prefill_chunk`` turns on CHUNKED PREFILL (Sarathi-Serve, Agrawal
  et al. 2024): prompts prefill as a sequence of chunk-sized compiled
  programs appended into the slot cache, at most one chunk per pool
  per ``step()``, so a max-length prompt costs its live neighbors one
  chunk quantum per token instead of freezing them for the whole
  prefill.  Deadlines are re-checked between chunks.

Both levers preserve greedy parity bit-for-bit (prefix rows are
position-causal, the chunk path runs the exact dense cache read) and
keep the compiled program set bounded; with both off, the legacy
one-shot prefill path is byte-identical to before.

Disaggregated prefill/decode (ISSUE 19): because prefix-store segments
and KV pages are the same ``[1, KVH, align, D]`` blocks, a finished
prefill's cache is a SHIPPABLE currency.  ``export_prefix`` pulls a
prompt's cached blocks out of the store as host arrays,
``import_prefix`` installs a shipped block set into another engine's
store (admission then takes the ordinary prefix-hit path, so the
decode-side tokens are byte-identical to a monolithic engine by
construction), and ``match_blocks`` is the cluster-tier lookup —
check local blocks before asking the prefill pool's store.
``pack_kv_blocks`` / ``unpack_kv_blocks`` are the wire codec (scope
``"kv"``, gather-sent page memoryviews behind a length-prefixed
msgpack meta); ``gateway.PrefillDecodeRouter`` drives the pipeline.

Observability (``distkeras_tpu.telemetry``; no-op until
``telemetry.enable()``): per-bucket ``serving_queue_depth`` /
``serving_slot_occupancy`` gauges, ``serving_ttft_seconds`` /
``serving_latency_seconds`` / ``serving_inter_token_seconds``
histograms (the latter feeds the watchdog's ``inter_token_p99``
signal), token/request/finish counters,
trace-time ``compiles_total{kind,bucket[,padded]}`` (the public face
of ``compile_counts``), and ``prefill``/``decode_step`` spans +
``evict`` instants on the serving thread's timeline track.  The
prefix/chunk layer adds ``serving_prefix_{hits,misses,evictions,
invalidations}_total``, ``serving_prefill_tokens_saved_total``, the
``serving_prefix_hit_rate`` gauge (an SLO watchdog signal),
``prefix_copy``/``prefill_chunk`` spans, and a ``prefix_invalidate``
flight-recorder event on every store invalidation.  Request timing
stamps all read ``telemetry.now()`` — see ``_finish``.
"""

from __future__ import annotations

import collections
import struct
import threading
from typing import Iterable, Iterator, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import flight_recorder, paging, telemetry
from distkeras_tpu import speculative as _speculative
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.models.generate import (_decode_model, _select,
                                           decode_step)
from distkeras_tpu.parallel import transport

_UNSET = object()


class ShedError(RuntimeError):
    """``submit`` refused a request — admission-control load shedding
    (Orca-style: reject at the door under overload instead of letting
    the queue grow without bound).  ``reason`` is the machine-readable
    cause (currently ``"queue_full"``); every shed also increments the
    ``serving_shed_total{reason,bucket}`` counter.  The request never
    entered the engine: resubmit after draining, or drop it."""

    def __init__(self, reason: str, detail: str):
        super().__init__(detail)
        self.reason = reason


def _ceil_to(n: int, align: int) -> int:
    return -(-n // align) * align


# ---------------------------------------------------------------------
# KV page-block wire codec (ISSUE 19, wire scope "kv")
# ---------------------------------------------------------------------
#
# One exported block set travels as ONE transport frame:
#   b"K" + meta_len(8B BE) + pack_obj(meta) + block0 leaves + block1 ...
# where meta carries the prompt, the block count, the exporter's
# weights version, and one shape/dtype template per cache leaf
# (``paging.leaf_templates`` — every block of an export shares them).
# The raw leaf bytes carry NO per-part framing: the receiver slices
# the body by the templates' byte sizes, so the send side can gather-
# send page memoryviews with zero copies (``transport.send_msg_gather``).

_KV_META_HDR = struct.Struct(">Q")


def _np_dtype(name: str) -> np.dtype:
    """``np.dtype`` by name, falling back to the ml_dtypes extension
    types (bfloat16 et al.) that numpy only knows once registered."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def pack_kv_blocks(export: Mapping) -> list:
    """Wire parts for one ``export_prefix`` result, ready for
    ``transport.send_msg_gather`` (or ``b"".join`` for tests).  The
    leaf arrays ride as memoryviews — no ``tobytes`` copies."""
    blocks = export.get("blocks") or []
    meta = {"prompt": np.ascontiguousarray(export["prompt"],
                                           dtype=np.int32),
            "n_blocks": int(len(blocks)),
            "weights_ver": int(export.get("weights_ver", 0)),
            "leaves": (paging.leaf_templates(blocks[0])
                       if blocks else [])}
    mb = transport.pack_obj(meta)
    parts: list = [b"K", _KV_META_HDR.pack(len(mb)), mb]
    for segs in blocks:
        for s in segs:
            # uint8 view: extension dtypes (bfloat16 et al.) have no
            # buffer-protocol format, but their bytes ride fine
            parts.append(np.ascontiguousarray(
                np.asarray(s)).view(np.uint8).data)
    return parts


def unpack_kv_blocks(body) -> dict:
    """Inverse of ``pack_kv_blocks`` over a received frame body
    (bytes or the ``recv_msg_into`` memoryview): returns the export
    dict with host-array blocks.  Rejects a malformed frame loudly —
    a desynced stream must not install garbage KV."""
    body = memoryview(body)
    if body.nbytes < 1 + _KV_META_HDR.size or bytes(body[:1]) != b"K":
        raise ValueError("not a kv page_blocks frame")
    (mlen,) = _KV_META_HDR.unpack(bytes(body[1:1 + _KV_META_HDR.size]))
    off = 1 + _KV_META_HDR.size
    if off + mlen > body.nbytes:
        raise ValueError("kv frame meta overruns the body")
    meta = transport.unpack_obj(body[off:off + mlen])
    off += mlen
    n_blocks = int(meta["n_blocks"])
    tmpls = [(_np_dtype(t["dtype"]),
              tuple(int(d) for d in t["shape"])) for t in meta["leaves"]]
    blocks = []
    for _ in range(n_blocks):
        segs = []
        for dt, shape in tmpls:
            nb = dt.itemsize * int(np.prod(shape, dtype=np.int64))
            if off + nb > body.nbytes:
                raise ValueError("kv frame leaf overruns the body")
            segs.append(np.frombuffer(body[off:off + nb],
                                      dtype=dt).reshape(shape))
            off += nb
        blocks.append(segs)
    if off != body.nbytes:
        raise ValueError(
            f"kv frame length mismatch: parsed {off} of "
            f"{body.nbytes} bytes")
    return {"prompt": np.asarray(meta["prompt"], np.int32),
            "n_blocks": n_blocks,
            "weights_ver": int(meta["weights_ver"]),
            "blocks": blocks}


class _Request:
    __slots__ = ("rid", "prompt", "max_new", "eos_id", "tokens", "meta",
                 "submit_order", "t_submit", "t_first", "t_last_tok",
                 "traces_seen",
                 "deadline", "prefix_path", "weights_ver", "tenant",
                 "priority", "pages", "swap", "spec_on")

    def __init__(self, rid, prompt, max_new, eos_id, meta, submit_order,
                 deadline=None, tenant=None, priority=1):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.tokens: list[int] = []
        self.meta = meta
        self.submit_order = submit_order
        self.t_submit = telemetry.now()
        self.t_first = None
        self.t_last_tok = None         # inter-token gap anchor
        self.traces_seen = -1          # engine trace total at anchor
        # absolute telemetry.now() expiry (None: no deadline)
        self.deadline = (None if deadline is None
                         else self.t_submit + deadline)
        self.prefix_path: tuple = ()   # pinned store nodes (admit)
        self.weights_ver = -1          # engine weights at prefill time
        self.tenant = tenant           # QoS: quota accounting key
        self.priority = priority       # QoS: 0 (lowest) .. 2 (highest)
        self.pages: list[int] = []     # paged mode: held page ids
        self.swap = None               # parked: host KV / restore plan
        self.spec_on = None            # per-request speculative
        #                                override (None: engine config)

    def ledger(self, env: Optional[int] = None) -> np.ndarray:
        """The slot's ONE retained-token ledger: prompt + every
        generated token, most-recent-``env`` truncated when an
        envelope is given.  Both consumers — the recompute-preemption
        readmission arm and the n-gram drafter — read exactly this
        (the pre-speculation engine kept two copies of the
        truncation logic)."""
        ext = np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])
        return ext if env is None else ext[-env:]


class _PrefixNode:
    """One ``prefill_align``-sized block of a cached prefix: the K/V
    rows for its token block as device arrays (one ``[1, KVH, align,
    D|1]`` segment per 4-D cache leaf, in flatten order — envelope-
    free, so one store serves every bucket)."""

    __slots__ = ("key", "parent", "children", "segments", "nbytes",
                 "refs", "last_use")

    def __init__(self, key, parent, segments):
        self.key = key
        self.parent = parent
        self.children: dict = {}
        self.segments = segments
        self.nbytes = sum(int(s.nbytes) for s in segments)
        self.refs = 0
        self.last_use = 0


class _PrefixStore:
    """Host-side longest-prefix index over aligned token-id blocks
    (the RadixAttention idea at ``prefill_align`` granularity): a trie
    whose node at depth ``d`` holds block ``d``'s K/V segments.
    ``match`` walks a prompt's blocks to the longest cached path;
    donation inserts a finished request's blocks (dedup'd);
    ``evict_to_budget`` drops LRU childless unreferenced nodes until
    total bytes fit the budget (live refs are pinned).  Mutated only
    on the engine's stepping thread, except ``clear`` which the
    engine serializes under its admission lock."""

    def __init__(self, align: int, budget: int):
        self.align = align
        self.budget = budget
        self.root = _PrefixNode(None, None, [])
        self.nbytes = 0
        self.n_nodes = 0
        self._clock = 0
        self.hits = self.misses = 0
        self.evictions = self.invalidations = 0
        self.tokens_saved = 0

    def _touch(self, node: _PrefixNode) -> None:
        self._clock += 1
        node.last_use = self._clock

    def match(self, prompt, max_blocks: int) -> list[_PrefixNode]:
        """Longest cached path over ``prompt``'s aligned blocks (at
        most ``max_blocks`` — the caller caps it so at least one true
        token remains to prefill the first-token logits)."""
        node, path, a = self.root, [], self.align
        for b in range(max_blocks):
            child = node.children.get(
                prompt[b * a:(b + 1) * a].tobytes())
            if child is None:
                break
            path.append(child)
            node = child
        for n in path:
            self._touch(n)
        return path

    def insert(self, parent: _PrefixNode, key: bytes,
               segments) -> _PrefixNode:
        node = _PrefixNode(key, parent, segments)
        parent.children[key] = node
        self.nbytes += node.nbytes
        self.n_nodes += 1
        self._touch(node)
        return node

    def evict_to_budget(self) -> int:
        """LRU eviction to the byte budget: only childless nodes with
        zero refs are candidates (an interior node is implicitly
        pinned by its descendants; a refed node by its live
        requests), so eviction cascades leaf-first."""
        evicted = 0
        while self.nbytes > self.budget:
            victim = None

            def walk(node, victim=None):
                for child in node.children.values():
                    if not child.children and child.refs <= 0:
                        if (victim is None
                                or child.last_use < victim.last_use):
                            victim = child
                    else:
                        victim = walk(child, victim)
                return victim

            victim = walk(self.root)
            if victim is None:
                break  # everything left is pinned
            del victim.parent.children[victim.key]
            self.nbytes -= victim.nbytes
            self.n_nodes -= 1
            self.evictions += 1
            evicted += 1
        return evicted

    def clear(self) -> tuple:
        """Drop every cached segment (weight swap / close); returns
        ``(nodes, bytes)`` released.  Live requests keep their slot
        COPIES — only future admissions are affected."""
        n, b = self.n_nodes, self.nbytes
        self.root = _PrefixNode(None, None, [])
        self.n_nodes = 0
        self.nbytes = 0
        self.invalidations += 1
        return n, b


class _Pool:
    """One cache envelope: device pool + per-slot host bookkeeping."""

    __slots__ = ("env", "n_slots", "dec", "cache", "state", "reqs",
                 "step_fn", "prefill_fn", "queue", "chunk_fn",
                 "copy_fn", "extract_fn", "prefilling", "cache_tmpl",
                 "table", "table_np", "spec")

    def __init__(self, env, n_slots, dec):
        self.env = env
        self.n_slots = n_slots
        self.dec = dec
        self.reqs: list[Optional[_Request]] = [None] * n_slots
        self.queue: collections.deque[_Request] = collections.deque()
        # slot -> pending chunk-prefill plan (insertion order = the
        # order step() advances them, one chunk per pool per call)
        self.prefilling: dict = {}

    def live(self) -> bool:
        return any(r is not None for r in self.reqs)

    def decodable(self) -> bool:
        """At least one occupied slot is PAST its prefill — a decode
        step would produce a real token (mid-prefill slots ride along
        as done rows; a pool of only those skips the dispatch)."""
        return any(r is not None and s not in self.prefilling
                   for s, r in enumerate(self.reqs))


class DecodeEngine:
    """Slot-based continuous-batching server for ``TransformerLM``.

    Args:
      model: a ``TransformerLM``, its ``ModelSpec``, or a config dict
        (same contract as ``generate``; GQA / int8-cache / attention
        spellings compose — the prefill runs the model's resolved
        kernel, steps run the cached dense row).
      variables: ``{"params": ...}`` from init/training.
      slots: concurrent requests per bucket (the step program's batch).
      buckets: cache envelopes — ``None`` (one pool at ``max_len``), a
        sequence of envelope lengths (each gets ``slots`` slots), or a
        ``{envelope: slots}`` mapping.  A request is routed to the
        smallest envelope that fits ``padded_prompt + max_new_tokens``;
        per the §18 cost law its steps then pay only that envelope's
        static cache read.
      max_new_tokens: default per-request cap (``submit`` overrides).
      eos_id: default stop token (``submit`` overrides; None = none).
      prefill_align: prompts are right-padded to this multiple before
        prefill, bounding the compiled prefill shapes per bucket to
        ``envelope / prefill_align``.  Pad rows never pollute results:
        the true-last-token logits seed generation (``last_index``) and
        pad K/V sit beyond every live causal horizon until overwritten.
      steps_per_sync: decode steps per compiled dispatch.  1 = admit /
        evict at every token (maximal slot reuse); larger values
        amortize host round-trips at an admission granularity of that
        many tokens (the right lever when dispatch latency is large,
        e.g. the measured ~140 ms tunnel RTT).
      temperature/top_k/top_p/seed: sampling (0 = greedy, the
        admission-order-invariant mode).
      pad_id: prompt padding + post-eos filler token.
      donate: donate cache/state buffers to the compiled programs so
        the pool is updated in place (default: on for non-CPU
        backends; CPU XLA cannot always honor it and warns).
      queue_bound: bounded admission queue — per-bucket cap on WAITING
        requests.  At the bound, ``submit`` sheds: it raises
        ``ShedError(reason="queue_full")`` and counts
        ``serving_shed_total`` instead of queueing without bound
        (``None``: unbounded, the pre-fault-tolerance behavior).
      deadline: default per-request wall-clock budget in seconds (from
        submit; ``submit(deadline=...)`` overrides per request).  A
        request past its deadline — still queued, mid-prefill, or
        mid-decode — is finished with an ``error`` result instead of
        holding a slot or queue position (``None``: no deadline).
      prefix_cache_bytes: byte budget for the shared-prefix KV store
        (``None``: off).  Admitted prompts reuse the longest cached
        aligned prefix via a device-to-device copy (zero model
        FLOPs); finished requests donate their aligned prompt blocks
        back; LRU eviction beyond the budget skips segments pinned by
        live requests.  ``swap_variables`` invalidates the store.
      prefill_chunk: chunked-prefill quantum in tokens (``None``: off;
        must be a multiple of ``prefill_align``).  Prompts prefill as
        a sequence of at-most-this-long compiled chunk programs, at
        most one chunk per bucket per ``step()`` interleaved with
        decode, bounding live slots' inter-token latency by the chunk
        quantum instead of the longest neighbor prompt.  Deadlines
        are re-checked between chunks.
      kv_pages: number of usable device KV pages (``None``: the legacy
        envelope pools, byte-identical to before).  When set, every
        bucket's slots draw KV memory from ONE shared block-paged pool
        (``distkeras_tpu.paging``): a slot costs its actual token
        count rounded up to a page instead of a whole envelope, so the
        ``cache_envelope x slots`` memory cliff disappears and the
        sustainable concurrency at a fixed byte budget is set by the
        traffic, not the worst case.  Compiled programs gather a
        slot's pages into the envelope layout, run the UNCHANGED
        legacy compute, and scatter back — greedy results stay
        byte-identical to the envelope path.  Every bucket envelope
        must be a multiple of ``page_size``.
      page_size: tokens per KV page (default: ``prefill_align``; must
        equal it while ``prefix_cache_bytes`` is set, so prefix-store
        segments and pages are the same shape and prefix sharing +
        paging are one mechanism).
      preemption: pool-exhaustion policy in paged mode — ``"swap"``
        (default) parks the lowest-priority live request with its
        pages swapped to host memory and restores it page-exact when
        pages free up; ``"recompute"`` parks without saving KV and
        re-prefills prompt + generated tokens at readmission (cheaper
        in host memory, re-pays the prefill FLOPs); ``"none"``
        disables preemption (an exhausted pool sheds the growing
        request with ``error="kv_pages_exhausted"``).
      recompute_below: with ``preemption="swap"``, victims whose
        context (prompt + generated) is at most this many tokens are
        recompute-parked instead of swapped — below the threshold the
        re-prefill is cheaper than the host round-trip (0: always
        swap).
      tenant_quota: per-tenant page cap enforced at admission (int:
        every tenant; mapping: listed tenants, others unbounded;
        ``None``: off).  A quota-blocked request waits in the queue
        while others admit past it — quotas cannot be fixed by
        preemption.
      speculative: speculative-decoding config (``None``: off) — a
        mapping with ``proposer`` (``"ngram"``: model-free
        prompt-lookup over the slot's token ledger; ``"draft"``: a
        smaller same-vocab model with its own per-pool envelope KV),
        ``k`` (proposal window, default 4), ``ngram`` (match length,
        default 2), and for the draft proposer ``draft_model`` +
        ``draft_variables``.  Each step, every eligible slot's
        proposer guesses up to ``k`` tokens and ONE dense verify
        pass scores all ``k + 1`` positions (the chunk-prefill
        machinery with ``logits_all``); the longest prefix the
        target model itself would have produced is committed plus
        one bonus token, the rest rolled back by rewinding the slot
        position (envelope) or freeing tail page-table entries
        (paged) — greedy output is byte-identical to the
        non-speculative engine by construction.  Requires
        ``temperature=0.0`` and ``steps_per_sync=1``; composes with
        chunked prefill, the prefix store, preemption (draft KV is
        recompute-class, never swapped), and ``swap_variables``
        (drafts are invalidated with the weights version).
        ``submit(speculative=False)`` opts a request out.
    """

    def __init__(self, model, variables: Mapping, *, slots: int = 8,
                 buckets=None, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 prefill_align: int = 128, steps_per_sync: int = 1,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, seed: int = 0,
                 donate: Optional[bool] = None,
                 queue_bound: Optional[int] = None,
                 deadline: Optional[float] = None,
                 prefix_cache_bytes: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 page_size: Optional[int] = None,
                 preemption: str = "swap",
                 recompute_below: int = 0,
                 tenant_quota=None,
                 speculative=None):
        base = _decode_model(model)
        self.max_len = base.max_len
        self.vocab_size = base.vocab_size
        if slots < 1:
            raise ValueError(f"slots must be >= 1; got {slots}")
        if prefill_align < 1:
            raise ValueError(
                f"prefill_align must be >= 1; got {prefill_align}")
        if steps_per_sync < 1:
            raise ValueError(
                f"steps_per_sync must be >= 1; got {steps_per_sync}")
        if max_new_tokens is not None and max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}")
        for name, tok in (("eos_id", eos_id), ("pad_id", pad_id)):
            if tok is not None and not 0 <= tok < base.vocab_size:
                raise ValueError(
                    f"{name}={tok} outside vocab [0, {base.vocab_size})")
        if top_k is not None and not 1 <= top_k <= base.vocab_size:
            raise ValueError(
                f"top_k={top_k} out of range [1, {base.vocab_size}]")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p={top_p} out of range (0, 1]")
        if queue_bound is not None and queue_bound < 1:
            raise ValueError(
                f"queue_bound must be >= 1 (or None); got {queue_bound}")
        if deadline is not None and deadline <= 0:
            raise ValueError(
                f"deadline must be positive seconds (or None); got "
                f"{deadline}")
        if prefix_cache_bytes is not None and prefix_cache_bytes < 1:
            raise ValueError(
                f"prefix_cache_bytes must be >= 1 (or None); got "
                f"{prefix_cache_bytes}")
        if prefill_chunk is not None and (
                prefill_chunk < prefill_align
                or prefill_chunk % prefill_align):
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be a positive "
                f"multiple of prefill_align={prefill_align} — chunk "
                "boundaries must land on the padded-shape grid")
        if kv_pages is not None and kv_pages < 1:
            raise ValueError(
                f"kv_pages must be >= 1 (or None); got {kv_pages}")
        if page_size is None:
            page_size = prefill_align
        if page_size < 1:
            raise ValueError(
                f"page_size must be >= 1; got {page_size}")
        if (kv_pages is not None and prefix_cache_bytes is not None
                and page_size != prefill_align):
            raise ValueError(
                f"page_size={page_size} must equal prefill_align="
                f"{prefill_align} while prefix_cache_bytes is set — "
                "prefix-store segments and KV pages must be the same "
                "shape for zero-copy interchange")
        if preemption not in ("swap", "recompute", "none"):
            raise ValueError(
                f"preemption must be 'swap', 'recompute', or 'none'; "
                f"got {preemption!r}")
        if recompute_below < 0:
            raise ValueError(
                f"recompute_below must be >= 0 tokens; got "
                f"{recompute_below}")
        if tenant_quota is not None and not isinstance(
                tenant_quota, Mapping) and int(tenant_quota) < 1:
            raise ValueError(
                f"tenant_quota must be >= 1 pages (or a mapping, or "
                f"None); got {tenant_quota}")
        spec = _speculative.normalize(speculative,
                                      vocab_size=self.vocab_size,
                                      max_len=self.max_len)
        if spec is not None:
            if float(temperature) != 0.0:
                raise ValueError(
                    "speculative decoding requires temperature=0.0 — "
                    "the acceptance rule is the greedy one (byte-"
                    f"identical output); got {temperature}")
            if steps_per_sync != 1:
                raise ValueError(
                    "speculative decoding requires steps_per_sync=1 — "
                    "a verify already commits up to k+1 tokens per "
                    f"host sync; got {steps_per_sync}")
        if buckets is None:
            buckets = {self.max_len: slots}
        elif isinstance(buckets, Mapping):
            buckets = dict(buckets)
        else:
            buckets = {int(env): slots for env in buckets}
        if len(buckets) == 0:
            raise ValueError("buckets must name at least one envelope")
        for env, n in buckets.items():
            if not 0 < env <= self.max_len:
                raise ValueError(
                    f"bucket envelope {env} outside (0, max_len="
                    f"{self.max_len}]")
            if n < 1:
                raise ValueError(
                    f"bucket {env} needs >= 1 slots; got {n}")
            if kv_pages is not None and env % page_size:
                raise ValueError(
                    f"bucket envelope {env} is not a multiple of "
                    f"page_size={page_size} — the paged gather/"
                    "scatter needs a whole number of pages per "
                    "envelope")
        self.variables = dict(variables)  # guarded-by: _lock
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        self.prefill_align = int(prefill_align)
        self.steps_per_sync = int(steps_per_sync)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.queue_bound = queue_bound
        self.deadline = deadline
        self.prefix_cache_bytes = prefix_cache_bytes
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        # either lever routes admission through the segmented path;
        # with both off the legacy one-shot prefill is untouched
        self._segmented = (prefix_cache_bytes is not None
                           or prefill_chunk is not None)
        self._prefix = (_PrefixStore(self.prefill_align,
                                     int(prefix_cache_bytes))
                        if prefix_cache_bytes is not None else None)
        self.kv_pages = kv_pages
        self.page_size = int(page_size)
        self.preemption = preemption
        self.recompute_below = int(recompute_below)
        self._paged = kv_pages is not None
        self._alloc = (paging.PageAllocator(kv_pages, self.page_size,
                                            tenant_quota)
                       if self._paged else None)
        self._pages = None       # shared device page pool (paged mode)
        self._parked = []        # preempted, awaiting readmission
        self._page_copy_fn = None
        self._page_extract_fn = None
        self._weights_ver = 0  # guarded-by: _lock
        self._spec = spec
        self._spec_proposed = 0  # host mirrors of the spec counters
        self._spec_accepted = 0
        if spec is not None and spec["draft_model"] is not None:
            # device_put once: the draft weights ride every propose/
            # prefill dispatch and must not re-transfer per call
            spec["draft_variables"] = jax.tree_util.tree_map(
                jnp.asarray, spec["draft_variables"])
        self._key = jax.random.key(seed)
        self._n_rng = 0
        self._n_submitted = 0
        self._inflight: set = set()  # rids queued or in a slot
        # Admission lock: ``submit()`` is safe from any thread — it
        # serializes the queue/rid/dedup mutations against the
        # stepping thread's admission sweep (which pops under the same
        # lock but prefills OUTSIDE it, so submitters never wait on a
        # compiled program).  ``step()`` itself must still run on one
        # thread at a time — the gateway's ``EngineReplica`` gives
        # every engine a single driver thread by construction.
        self._lock = racecheck.rlock("serving.engine")
        self._closed = False  # guarded-by: _lock
        self._traces: collections.Counter = collections.Counter()
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self._donate = bool(donate)
        self._pools = []
        for env in sorted(buckets):
            dec = base if env == self.max_len else base.clone(
                cache_envelope=env)
            pool = _Pool(env, buckets[env], dec)
            self._init_pool(pool)
            self._pools.append(pool)

    # ---- compiled programs -------------------------------------------

    def _init_pool(self, pool: _Pool) -> None:
        s = pool.n_slots
        shapes = jax.eval_shape(
            lambda v: pool.dec.apply(v, jnp.zeros((s, 1), jnp.int32),
                                     mutable=["cache"]),
            {"params": self.variables["params"]})[1]["cache"]
        pool.cache_tmpl = shapes
        if self._paged:
            # no per-bucket envelope pool: slots read/write the shared
            # page pool through their table rows (all entries start at
            # the garbage page)
            pool.cache = None
            pool.table_np = np.zeros(
                (s, pool.env // self.page_size), np.int32)
            pool.table = jnp.asarray(pool.table_np)
            if self._pages is None:  # KVH/page/D are bucket-invariant
                self._pages = paging.build_pool(
                    shapes, self.kv_pages, self.page_size)
        else:
            pool.cache = jax.tree_util.tree_map(
                lambda sh: jnp.zeros(sh.shape, sh.dtype), shapes)
            pool.table = pool.table_np = None
        pool.state = {
            "tok": jnp.full((s,), self.pad_id, jnp.int32),
            "pos": jnp.zeros((s,), jnp.int32),
            "n_left": jnp.zeros((s,), jnp.int32),
            "eos": jnp.full((s,), -1, jnp.int32),
            "done": jnp.ones((s,), bool),
        }
        pool.step_fn = self._make_step(pool)
        pool.prefill_fn = self._make_prefill(pool)
        pool.chunk_fn = (self._make_chunk_prefill(pool)
                         if self._segmented else None)
        if self._spec is not None:
            k = self._spec["k"]
            pool.spec = {"verify_fns": {
                w: self._make_verify(pool, w) for w in (1, k + 1)}}
            if self._spec["draft_model"] is not None:
                pool.spec.update(self._init_draft(pool))
        else:
            pool.spec = None
        if self._paged:
            # paged prefix install/donation go page-direct (bucket-
            # independent shapes: ONE compiled pair for all pools)
            pool.copy_fn = pool.extract_fn = None
            if (self._prefix is not None
                    and self._page_copy_fn is None):
                self._page_copy_fn = self._make_page_copy()
                self._page_extract_fn = self._make_page_extract()
        else:
            pool.copy_fn = (self._make_prefix_copy(pool)
                            if self._prefix is not None else None)
            pool.extract_fn = (self._make_prefix_extract(pool)
                               if self._prefix is not None else None)

    def _make_step(self, pool: _Pool):
        dec, env = pool.dec, pool.env
        temp, top_k, top_p = self.temperature, self.top_k, self.top_p
        pad_id, n_sub = self.pad_id, self.steps_per_sync

        def step_core(variables, cache, state, rng):
            params = {"params": variables["params"]}

            def body(carry, sub):
                cache, st = carry
                fin = st["done"]
                # done slots re-write their last row (dead data, kept
                # in range so live rows never see the NaN poison)
                step_pos = jnp.minimum(st["pos"], env - 1)
                cache, nxt = decode_step(
                    dec, params, cache, st["tok"], slot_pos=step_pos,
                    temperature=temp, top_k=top_k, top_p=top_p,
                    rng=sub)
                eos_hit = (st["eos"] >= 0) & (nxt == st["eos"])
                nxt = jnp.where(fin, pad_id, nxt)
                n_left = jnp.where(fin, st["n_left"],
                                   st["n_left"] - 1)
                st = {"tok": nxt,
                      "pos": jnp.where(fin, st["pos"], st["pos"] + 1),
                      "n_left": n_left,
                      "eos": st["eos"],
                      "done": fin | eos_hit | (n_left <= 0)}
                return (cache, st), (nxt, fin)

            (cache, state), (toks, was_done) = jax.lax.scan(
                body, (cache, state), jax.random.split(rng, n_sub))
            # toks[k, s] is real iff the slot was live ENTERING sub-
            # step k (was_done[k, s] False); the host replays exactly
            # this predicate.
            return cache, state, toks, was_done

        if not self._paged:
            def step_impl(variables, cache, state, rng):
                # Python side effects: run at TRACE time only, so
                # these count compilations — the compile-guard test's
                # probe.  The registry counter sees only compiles that
                # happen while telemetry is enabled (enable before
                # construction).
                self._traces["step", env] += 1
                telemetry.metrics().counter(
                    "compiles_total", kind="step", bucket=env).inc()
                return step_core(variables, cache, state, rng)

            donate = (1, 2) if self._donate else ()
            return jax.jit(step_impl, donate_argnums=donate)

        tmpl = pool.cache_tmpl

        def paged_step_impl(variables, pages, table, state, rng):
            self._traces["paged_step", env] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="paged_step", bucket=env).inc()
            cache = paging.gather_cache(tmpl, pages, table)
            cache, state, toks, was_done = step_core(
                variables, cache, state, rng)
            return (paging.scatter_cache(pages, cache, table), state,
                    toks, was_done)

        donate = (1, 3) if self._donate else ()
        return jax.jit(paged_step_impl, donate_argnums=donate)

    def _make_prefill(self, pool: _Pool):
        dec, env = pool.dec, pool.env
        temp, top_k, top_p = self.temperature, self.top_k, self.top_p

        def prefill_core(variables, cache, state, prompt, slot,
                         last_idx, n_left0, eos_id, rng):
            params = {"params": variables["params"]}
            logits, st = dec.apply(params, prompt, mutable=["cache"],
                                   last_index=last_idx)
            tok0 = _select(logits[:, -1].astype(jnp.float32), temp,
                           top_k, top_p, rng)[0]

            def merge(pool_leaf, new_leaf):
                if jnp.ndim(new_leaf) == 0:  # scalar cache/pos index:
                    return pool_leaf         # slot state owns positions
                return jax.lax.dynamic_update_slice(
                    pool_leaf, new_leaf,
                    (slot,) + (0,) * (new_leaf.ndim - 1))

            # the WHOLE envelope is replaced, so a dirty evicted slot
            # is clean by construction on readmission
            cache = jax.tree_util.tree_map(merge, cache, st["cache"])
            done0 = (n_left0 <= 0) | ((eos_id >= 0) & (tok0 == eos_id))
            state = {
                "tok": state["tok"].at[slot].set(tok0),
                "pos": state["pos"].at[slot].set(last_idx + 1),
                "n_left": state["n_left"].at[slot].set(n_left0),
                "eos": state["eos"].at[slot].set(eos_id),
                "done": state["done"].at[slot].set(done0),
            }
            return cache, state, tok0

        if not self._paged:
            def prefill_impl(variables, cache, state, prompt, slot,
                             last_idx, n_left0, eos_id, rng):
                # trace-time counter: one compile per (bucket, padded
                # prompt length) — the bounded prefill program set
                self._traces["prefill", env, prompt.shape[1]] += 1
                telemetry.metrics().counter(
                    "compiles_total", kind="prefill", bucket=env,
                    padded=prompt.shape[1]).inc()
                return prefill_core(variables, cache, state, prompt,
                                    slot, last_idx, n_left0, eos_id,
                                    rng)

            donate = (1, 2) if self._donate else ()
            return jax.jit(prefill_impl, donate_argnums=donate)

        tmpl = pool.cache_tmpl

        def paged_prefill_impl(variables, pages, table, state, prompt,
                               slot, last_idx, n_left0, eos_id, rng):
            self._traces["paged_prefill", env, prompt.shape[1]] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="paged_prefill", bucket=env,
                padded=prompt.shape[1]).inc()
            cache = paging.gather_cache(tmpl, pages, table)
            cache, state, tok0 = prefill_core(
                variables, cache, state, prompt, slot, last_idx,
                n_left0, eos_id, rng)
            return (paging.scatter_cache(pages, cache, table), state,
                    tok0)

        donate = (1, 3) if self._donate else ()
        return jax.jit(paged_prefill_impl, donate_argnums=donate)

    def _make_chunk_prefill(self, pool: _Pool):
        """One compiled program per (bucket, chunk length) appending a
        mid-prompt chunk into a slot's cache rows ``[start, start+T)``:
        the slot's envelope is sliced out of the pool, the scalar
        cache/pos indices are pointed at ``start``, and a DENSE-
        attention clone runs the chunk (the blocked prefill kernels
        are exact only from an empty cache; the dense cache read is
        exact at ANY offset — rows at/after ``start`` are causally
        masked until this very call overwrites them).  Slot state is
        installed by the FINAL chunk only; until then the slot stays
        ``done`` with its dead-write row parked at ``env - 1``, which
        interleaved decode steps may rewrite harmlessly (a slot reads
        that row only after overwriting it itself)."""
        env = pool.env
        dense = pool.dec.clone(attn="dense", attn_fn=None,
                               flash_attn=False, blockwise_attn=False)
        temp, top_k, top_p = self.temperature, self.top_k, self.top_p
        pad_id = self.pad_id

        def chunk_core(variables, cache, state, chunk, slot, start,
                       last_rel, is_final, n_left0, eos_id, rng):
            params = {"params": variables["params"]}

            def pick(leaf):
                if jnp.ndim(leaf) == 0:  # cache/pos index: the offset
                    return jnp.asarray(start, leaf.dtype)
                return jax.lax.dynamic_slice(
                    leaf, (slot,) + (0,) * (leaf.ndim - 1),
                    (1,) + leaf.shape[1:])

            sub = jax.tree_util.tree_map(pick, cache)
            logits, st = dense.apply({**params, "cache": sub}, chunk,
                                     mutable=["cache"],
                                     last_index=last_rel)
            tok0 = _select(logits[:, -1].astype(jnp.float32), temp,
                           top_k, top_p, rng)[0]

            def merge(pool_leaf, new_leaf):
                if jnp.ndim(new_leaf) == 0:
                    return pool_leaf
                return jax.lax.dynamic_update_slice(
                    pool_leaf, new_leaf,
                    (slot,) + (0,) * (new_leaf.ndim - 1))

            # rows outside [start, start+T) of the sub-envelope are
            # the pool's own rows read back unchanged, so the whole-
            # envelope merge equals a chunk-rows-only write
            cache = jax.tree_util.tree_map(merge, cache, st["cache"])
            done0 = (n_left0 <= 0) | ((eos_id >= 0) & (tok0 == eos_id))
            state = {
                "tok": state["tok"].at[slot].set(
                    jnp.where(is_final, tok0, pad_id)),
                "pos": state["pos"].at[slot].set(
                    jnp.where(is_final, start + last_rel + 1,
                              env - 1)),
                "n_left": state["n_left"].at[slot].set(
                    jnp.where(is_final, n_left0, 0)),
                "eos": state["eos"].at[slot].set(
                    jnp.where(is_final, eos_id, -1)),
                "done": state["done"].at[slot].set(
                    jnp.where(is_final, done0, True)),
            }
            return cache, state, tok0

        if not self._paged:
            def chunk_impl(variables, cache, state, chunk, slot,
                           start, last_rel, is_final, n_left0, eos_id,
                           rng):
                t_c = chunk.shape[1]
                self._traces["chunk_prefill", env, t_c] += 1
                telemetry.metrics().counter(
                    "compiles_total", kind="chunk_prefill", bucket=env,
                    padded=t_c).inc()
                return chunk_core(variables, cache, state, chunk,
                                  slot, start, last_rel, is_final,
                                  n_left0, eos_id, rng)

            donate = (1, 2) if self._donate else ()
            return jax.jit(chunk_impl, donate_argnums=donate)

        tmpl = pool.cache_tmpl

        def paged_chunk_impl(variables, pages, table, state, chunk,
                             slot, start, last_rel, is_final, n_left0,
                             eos_id, rng):
            t_c = chunk.shape[1]
            self._traces["paged_chunk_prefill", env, t_c] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="paged_chunk_prefill",
                bucket=env, padded=t_c).inc()
            cache = paging.gather_cache(tmpl, pages, table)
            cache, state, tok0 = chunk_core(
                variables, cache, state, chunk, slot, start, last_rel,
                is_final, n_left0, eos_id, rng)
            return (paging.scatter_cache(pages, cache, table), state,
                    tok0)

        donate = (1, 3) if self._donate else ()
        return jax.jit(paged_chunk_impl, donate_argnums=donate)

    def _make_verify(self, pool: _Pool, width: int):
        """The speculative VERIFY program: one dense-attention pass
        over a ``[1, width]`` chunk — ``[last committed token,
        proposal_1 .. proposal_{width-1}]`` — sliced into the slot's
        envelope at the scalar cache offset (exactly the chunk-
        prefill machinery), but with ``logits_all`` so EVERY
        position's greedy argmax comes back: ``greedy[j]`` is what
        the target model itself generates after proposal ``j`` tokens
        of the window, which is simultaneously the acceptance oracle
        for proposal ``j+1`` and the bonus token when acceptance ends
        at ``j``.  K/V rows for rejected proposals are left in place
        and rolled back by rewinding the slot position — the standing
        write-before-read argument makes the stale rows dead.  Two
        widths exist per bucket (``k + 1`` and the single-token
        fallback), so the compiled program set stays bounded."""
        env = pool.env
        dense = pool.dec.clone(attn="dense", attn_fn=None,
                               flash_attn=False, blockwise_attn=False)

        def verify_core(variables, cache, chunk, slot, start):
            params = {"params": variables["params"]}

            def pick(leaf):
                if jnp.ndim(leaf) == 0:  # cache/pos index: the offset
                    return jnp.asarray(start, leaf.dtype)
                return jax.lax.dynamic_slice(
                    leaf, (slot,) + (0,) * (leaf.ndim - 1),
                    (1,) + leaf.shape[1:])

            sub = jax.tree_util.tree_map(pick, cache)
            logits, st = dense.apply({**params, "cache": sub}, chunk,
                                     mutable=["cache"],
                                     logits_all=True)
            greedy = jnp.argmax(logits[0].astype(jnp.float32),
                                axis=-1).astype(jnp.int32)

            def merge(pool_leaf, new_leaf):
                if jnp.ndim(new_leaf) == 0:
                    return pool_leaf
                return jax.lax.dynamic_update_slice(
                    pool_leaf, new_leaf,
                    (slot,) + (0,) * (new_leaf.ndim - 1))

            cache = jax.tree_util.tree_map(merge, cache, st["cache"])
            return cache, greedy

        if not self._paged:
            def verify_impl(variables, cache, chunk, slot, start):
                self._traces["verify", env, width] += 1
                telemetry.metrics().counter(
                    "compiles_total", kind="verify", bucket=env,
                    padded=width).inc()
                return verify_core(variables, cache, chunk, slot,
                                   start)

            donate = (1,) if self._donate else ()
            return jax.jit(verify_impl, donate_argnums=donate)

        tmpl = pool.cache_tmpl

        def paged_verify_impl(variables, pages, table, chunk, slot,
                              start):
            self._traces["paged_verify", env, width] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="paged_verify", bucket=env,
                padded=width).inc()
            cache = paging.gather_cache(tmpl, pages, table)
            cache, greedy = verify_core(variables, cache, chunk,
                                        slot, start)
            return paging.scatter_cache(pages, cache, table), greedy

        donate = (1,) if self._donate else ()
        return jax.jit(paged_verify_impl, donate_argnums=donate)

    def _init_draft(self, pool: _Pool) -> dict:
        """Per-pool draft-proposer state: the draft model cloned at
        the bucket envelope, its own ``[slots, ...]`` ENVELOPE cache
        (never paged — draft KV is recompute-class state, rebuilt
        from the token ledger whenever invalidated, so the paged
        pool's swap machinery has nothing to preserve), host mirrors
        of each slot's draft feed token / position (``dpos == -1``
        means invalid: rebuild before proposing), and the compiled
        propose/prefill programs under the engine's compile guard."""
        s = pool.n_slots
        base = self._spec["draft_model"]
        ddec = (base if pool.env == base.max_len
                else base.clone(cache_envelope=pool.env))
        dshapes = jax.eval_shape(
            lambda v: ddec.apply(v, jnp.zeros((s, 1), jnp.int32),
                                 mutable=["cache"]),
            {"params": self._spec["draft_variables"]["params"]}
        )[1]["cache"]
        dcache = jax.tree_util.tree_map(
            lambda sh: jnp.zeros(sh.shape, sh.dtype), dshapes)
        env, k = pool.env, self._spec["k"]

        def note_step():
            self._traces["draft_step", env] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="draft_step", bucket=env).inc()

        def note_prefill(t_pad):
            self._traces["draft_prefill", env, t_pad] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="draft_prefill", bucket=env,
                padded=t_pad).inc()

        donate = (1,) if self._donate else ()
        return {
            "dec": ddec, "cache": dcache,
            "dtok": np.full((s,), self.pad_id, np.int32),
            "dpos": np.full((s,), -1, np.int32),
            "propose_fn": jax.jit(
                _speculative.make_draft_propose(
                    ddec, env, k, self.pad_id, on_trace=note_step),
                donate_argnums=donate),
            "prefill_fn": jax.jit(
                _speculative.make_draft_prefill(
                    ddec, on_trace=note_prefill),
                donate_argnums=donate),
        }

    def _make_page_copy(self):
        """Prefix-store install in paged mode: write one cached
        ``align``-row segment straight into an allocated page — the
        page IS the slot's block, no envelope in between.  Shapes are
        bucket-invariant, so this is ONE compiled program for the
        whole engine."""
        def page_copy_impl(pages, segments, pid):
            self._traces["page_copy", self.page_size] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="page_copy",
                bucket=self.page_size).inc()
            return [p.at[pid].set(s[0])
                    for p, s in zip(pages, segments)]

        donate = (0,) if self._donate else ()
        return jax.jit(page_copy_impl, donate_argnums=donate)

    def _make_page_extract(self):
        """Prefix donation in paged mode: slice one page out as a
        ``[1, KVH, page, D]`` store segment (fresh buffers — the pool
        keeps its own).  One compiled program for the engine."""
        def page_extract_impl(pages, pid):
            self._traces["page_extract", self.page_size] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="page_extract",
                bucket=self.page_size).inc()
            return [p[pid][None] for p in pages]

        return jax.jit(page_extract_impl)

    def _make_prefix_copy(self, pool: _Pool):
        """Device-to-device install of one cached ``align``-row block
        into a slot (zero model FLOPs — the prefill work the prefix
        cache eliminates).  One trace per bucket."""
        env = pool.env

        def copy_impl(cache, segments, slot, start):
            self._traces["prefix_copy", env] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="prefix_copy",
                bucket=env).inc()
            leaves, treedef = jax.tree_util.tree_flatten(cache)
            segs = iter(segments)
            out = []
            for leaf in leaves:
                if jnp.ndim(leaf) == 0:  # slot state owns positions
                    out.append(leaf)
                    continue
                out.append(jax.lax.dynamic_update_slice(
                    leaf, next(segs), (slot, 0, start, 0)))
            return jax.tree_util.tree_unflatten(treedef, out)

        donate = (0,) if self._donate else ()
        return jax.jit(copy_impl, donate_argnums=donate)

    def _make_prefix_extract(self, pool: _Pool):
        """Slice one ``align``-row block of a slot's cache out for
        donation to the store — NO donation here: the pool keeps its
        buffers, the store gets fresh ones.  One trace per bucket."""
        env, align = pool.env, self.prefill_align

        def extract_impl(cache, slot, start):
            self._traces["prefix_extract", env] += 1
            telemetry.metrics().counter(
                "compiles_total", kind="prefix_extract",
                bucket=env).inc()
            out = []
            for leaf in jax.tree_util.tree_leaves(cache):
                if jnp.ndim(leaf) == 0:
                    continue
                out.append(jax.lax.dynamic_slice(
                    leaf, (slot, 0, start, 0),
                    (1, leaf.shape[1], align, leaf.shape[3])))
            return out

        return jax.jit(extract_impl)

    # ---- admission ----------------------------------------------------

    def _route(self, t_p: int, max_new: int) -> _Pool:
        for pool in self._pools:  # ascending envelopes
            t_pad = min(pool.env, _ceil_to(t_p, self.prefill_align))
            if t_p <= t_pad <= pool.env and t_p + max_new <= pool.env:
                return pool
        raise ValueError(
            f"prompt length {t_p} + max_new_tokens {max_new} fits no "
            f"bucket (envelopes "
            f"{[p.env for p in self._pools]}, max_len={self.max_len})")

    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               eos_id=_UNSET, request_id=None, deadline=_UNSET,
               meta: Optional[Mapping] = None, tenant=None,
               priority: int = 1, speculative=None):
        """Queue one request; returns its id (auto-assigned if None).

        ``max_new_tokens``/``eos_id``/``deadline`` default to the
        engine's; the request fails HERE if it fits no bucket, never
        inside a later compiled flush.  A ``request_id`` equal to one
        still in flight is rejected (results would cross-deliver);
        auto-assigned ids skip over in-flight explicit ids.  With
        ``queue_bound`` set, a full admission queue sheds the request
        (``ShedError``) instead of accepting it.

        ``tenant``/``priority`` are the paged-mode QoS keys (accepted
        but inert on the envelope path): admission picks the highest
        priority class (2 > 1 > 0, FIFO within a class), per-tenant
        page quotas are enforced at admission, and on pool exhaustion
        a higher-priority request preempts the lowest-priority live
        one instead of waiting behind it.

        ``speculative`` is the per-request override of the engine's
        speculative-decoding config: ``None`` follows the engine,
        ``False`` opts this request out (it decodes via the
        single-token verify — still byte-identical), ``True`` is an
        explicit opt-in and REQUIRES the engine to be configured
        with ``speculative=`` (rejected here otherwise — a silent
        no-op would hide a misconfigured client).
        """
        if self._closed:
            raise RuntimeError("engine is closed; submit after close()")
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or len(prompt) < 1:
            raise ValueError(
                f"prompt must be a 1-D token-id array; got shape "
                f"{prompt.shape}")
        max_new = (self.max_new_tokens if max_new_tokens is None
                   else max_new_tokens)
        if max_new is None or max_new < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (set per request or as "
                f"the engine default); got {max_new}")
        eos = self.eos_id if eos_id is _UNSET else eos_id
        if eos is not None and not 0 <= eos < self.vocab_size:
            raise ValueError(
                f"eos_id={eos} outside vocab [0, {self.vocab_size})")
        dl = self.deadline if deadline is _UNSET else deadline
        if dl is not None and dl <= 0:
            raise ValueError(
                f"deadline must be positive seconds (or None); got "
                f"{dl}")
        if not isinstance(priority, int) or not 0 <= priority <= 2:
            raise ValueError(
                f"priority must be an int in 0..2; got {priority!r}")
        if speculative and self._spec is None:
            raise ValueError(
                "submit(speculative=True) needs an engine built with "
                "speculative=...; this engine has speculation off")
        pool = self._route(len(prompt), max_new)
        if self._paged:
            # worst-case page footprint must fit the pool AND the
            # tenant's whole quota, else the request could park
            # forever — reject at the door like an unroutable prompt
            t_p = len(prompt)
            t_pad = min(pool.env, _ceil_to(t_p, self.prefill_align))
            need = max(paging.pages_for(t_pad, self.page_size),
                       paging.pages_for(min(pool.env, t_p + max_new),
                                        self.page_size))
            if need > self.kv_pages:
                raise ValueError(
                    f"request needs {need} KV pages at its max length "
                    f"but the pool has kv_pages={self.kv_pages}")
            quota = self._alloc.quota_for(tenant)
            if quota is not None and need > quota:
                raise ValueError(
                    f"request needs {need} KV pages at its max length "
                    f"but tenant {tenant!r} has a tenant_quota of "
                    f"{quota}")
        m = telemetry.metrics()
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "engine is closed; submit after close()")
            if (self.queue_bound is not None
                    and len(pool.queue) >= self.queue_bound):
                m.counter("serving_shed_total", reason="queue_full",
                          bucket=pool.env).inc()
                # lint: allow(blocking-call-under-lock): the shed
                # decision and its evidence must be atomic vs a racing
                # drain re-opening admission
                flight_recorder.record("shed", reason="queue_full",
                                       bucket=pool.env)
                raise ShedError(
                    "queue_full",
                    f"bucket {pool.env} admission queue at its bound "
                    f"({self.queue_bound} waiting); request shed — "
                    "resubmit after draining")
            if request_id is None:
                rid = self._n_submitted
                while rid in self._inflight:  # skip in-flight ids
                    rid += 1
            else:
                rid = request_id
                if rid in self._inflight:
                    raise ValueError(
                        f"request_id {rid!r} is already in flight; "
                        "duplicate ids would cross-deliver results")
            req = _Request(rid, prompt, int(max_new), eos,
                           dict(meta or {}), self._n_submitted,
                           deadline=dl, tenant=tenant,
                           priority=priority)
            if speculative is not None:
                req.spec_on = bool(speculative)
            self._n_submitted += 1
            self._inflight.add(rid)
            pool.queue.append(req)
            m.counter("serving_requests_total", bucket=pool.env).inc()
            m.gauge("serving_queue_depth",
                    bucket=pool.env).set(len(pool.queue))
            return req.rid

    def _next_rng(self):
        self._n_rng += 1
        return jax.random.fold_in(self._key, self._n_rng)

    def reset_rng(self) -> None:
        """Rewind the sampling key stream so a replayed workload draws
        the same tokens (only meaningful when the engine is idle; the
        compiled programs and cache pools are untouched)."""
        if self.has_work():
            raise RuntimeError(
                "reset_rng with requests in flight would replay keys "
                "mid-stream; drain the engine first")
        self._n_rng = 0

    def swap_variables(self, variables: Mapping) -> None:
        """Hot weight swap: install a new parameter pytree WITHOUT
        recompiling — the compiled step/prefill programs take the
        weights as an argument, so a same-structure tree reuses every
        cached program (``compile_counts`` is unchanged by a swap; the
        tier-1 swap test pins this).

        The new tree must match the current one exactly in treedef,
        leaf shapes, and dtypes — a mismatch would silently retrace
        (new compiles mid-serving, the §23 bound broken), so it is
        rejected HERE.  The swap takes effect at the next step
        boundary: ``step()``/``_admit`` snapshot ``self.variables``
        once per call, so in-flight requests finish their current
        quantum on the old weights and every later token uses the new
        ones.  Live-slot KV caches are NOT invalidated — a
        mid-request swap serves a hybrid prefix (standard
        rolling-serve semantics); drain the engine first (the
        gateway's rolling update does) when that matters.  The
        PREFIX STORE however IS invalidated: cached prefix K/V was
        computed under the old weights, and reusing it after a swap
        would be silently wrong for every future hit."""
        if self._closed:
            raise RuntimeError("engine is closed; swap after close()")
        new = dict(variables)
        old_leaves, old_def = jax.tree_util.tree_flatten(self.variables)
        new_leaves, new_def = jax.tree_util.tree_flatten(new)
        if old_def != new_def:
            raise ValueError(
                f"swap_variables structure mismatch: engine has "
                f"{old_def}, got {new_def}")
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            o_sh, n_sh = jnp.shape(o), jnp.shape(n)
            o_dt = np.dtype(getattr(o, "dtype", np.asarray(o).dtype))
            n_dt = np.dtype(getattr(n, "dtype", np.asarray(n).dtype))
            if o_sh != n_sh or o_dt != n_dt:
                raise ValueError(
                    f"swap_variables leaf {i} mismatch: engine has "
                    f"{o_sh}/{o_dt}, got {n_sh}/{n_dt} — a swap must "
                    "not retrace the compiled programs")
        # device_put up front (PS centers arrive as read-only host
        # numpy): the step loop then reuses device buffers instead of
        # re-transferring the tree every dispatch
        new = jax.tree_util.tree_map(jnp.asarray, new)
        inval = None
        with self._lock:
            self.variables = new
            # in-flight requests whose KV was (partly) computed under
            # the old weights must not donate it back post-swap
            self._weights_ver += 1
            if self._prefix is not None:
                inval = self._prefix.clear()
            # in-flight DRAFTS are invalidated with the weights
            # version too: every slot's draft cache is rebuilt from
            # its token ledger before the next propose, so no
            # proposal spans the swap boundary
            for pool in self._pools:
                for slot in range(pool.n_slots):
                    self._draft_invalidate(pool, slot)
        telemetry.metrics().counter("serving_weight_swaps_total").inc()
        telemetry.instant("weight_swap")
        flight_recorder.record("weight_swap",
                               leaves=len(new_leaves))
        if inval is not None:
            n_nodes, n_bytes = inval
            telemetry.metrics().counter(
                "serving_prefix_invalidations_total").inc()
            telemetry.instant("prefix_invalidate", nodes=n_nodes,
                              bytes=n_bytes)
            flight_recorder.record("prefix_invalidate",
                                   nodes=n_nodes, bytes=n_bytes,
                                   reason="weight_swap")

    def _note_gauges(self, pool: _Pool) -> None:
        """Per-bucket queue-depth / slot-occupancy gauges — the levels
        an operator correlates with a TTFT spike (no-op while
        telemetry is disabled)."""
        m = telemetry.metrics()
        m.gauge("serving_queue_depth",
                bucket=pool.env).set(len(pool.queue))
        m.gauge("serving_slot_occupancy", bucket=pool.env).set(
            sum(r is not None for r in pool.reqs))
        if self._paged:
            m.gauge("serving_free_pages").set(self._alloc.n_free)

    def _shed_expired_queued(self, pool: _Pool) -> list[dict]:
        """Sweep the admission queue for requests already past their
        deadline — they leave with an ``error`` result instead of
        consuming a prefill + slot they can no longer use."""
        with self._lock:
            if not any(r.deadline is not None for r in pool.queue):
                return []
            now = telemetry.now()
            expired, alive = [], collections.deque()
            for req in pool.queue:
                (expired if req.deadline is not None
                 and now > req.deadline else alive).append(req)
            pool.queue = alive
        m = telemetry.metrics()
        out = []
        for req in expired:
            m.counter("serving_shed_total", reason="deadline",
                      bucket=pool.env).inc()
            out.append(self._finish_error(req, "deadline_exceeded",
                                          pool.env))
        return out

    # ---- paged-mode QoS: pages, preemption, readmission ---------------

    def _pages_needed(self, t_p: int, pool: _Pool) -> int:
        """Initial page footprint of a prompt: its padded prefill
        length (pad rows land in real pages too — they are dead by
        the write-before-read argument, but keeping them covered
        means the whole prefill scatter is page-backed)."""
        t_pad = min(pool.env, _ceil_to(t_p, self.prefill_align))
        return paging.pages_for(t_pad, self.page_size)

    def _alloc_pages(self, n: int, tenant) -> Optional[list]:
        pids = self._alloc.alloc(n, tenant)
        if pids:
            telemetry.metrics().counter(
                "serving_pages_allocated_total").inc(len(pids))
        return pids

    def _release_pages(self, req: _Request, pool: _Pool = None,
                       slot: Optional[int] = None) -> None:
        """Return a request's pages to the allocator and (when it held
        a slot) point the table row back at the garbage page.  Also
        drops any parked host KV.  Idempotent — every terminal path
        funnels through here."""
        if self._paged and req.pages:
            self._alloc.free(req.pages, req.tenant)
            telemetry.metrics().counter(
                "serving_pages_freed_total").inc(len(req.pages))
            req.pages = []
        req.swap = None
        if pool is not None and slot is not None and self._paged:
            pool.table_np[slot] = 0
            pool.table = jnp.asarray(pool.table_np)

    def _set_table_row(self, pool: _Pool, slot: int,
                       pages: list) -> None:
        pool.table_np[slot] = 0
        pool.table_np[slot, :len(pages)] = pages
        pool.table = jnp.asarray(pool.table_np)

    def _pick_queued(self, pool: _Pool) -> Optional[_Request]:
        """QoS admission order: highest priority class first, FIFO
        within a class; quota-blocked requests are skipped (left
        queued) so they never starve the pool for others."""
        with self._lock:
            best = None
            for req in pool.queue:
                if not self._alloc.fits_quota(
                        self._pages_needed(len(req.prompt), pool),
                        req.tenant):
                    continue
                key = (-req.priority, req.submit_order)
                if best is None or key < best[0]:
                    best = (key, req)
            if best is None:
                return None
            pool.queue.remove(best[1])
            return best[1]

    def _pick_victim(self, below: int, exclude=None):
        """Lowest-priority live decodable request strictly below
        priority ``below`` (latest-submitted first within a class) —
        the preemption victim.  Mid-prefill slots are not preempted
        (their restore plan would be partial)."""
        best = None
        for pool in self._pools:
            for slot, req in enumerate(pool.reqs):
                if (req is None or slot in pool.prefilling
                        or req is exclude or req.priority >= below):
                    continue
                key = (req.priority, -req.submit_order)
                if best is None or key < best[0]:
                    best = (key, pool, slot)
        return None if best is None else (best[1], best[2])

    def _preempt(self, pool: _Pool, slot: int, reason: str) -> None:
        """Evict a live request WITHOUT finishing it: swap its pages
        to host memory (or plan a recompute below the threshold /
        under ``preemption="recompute"``), free the pages, and park
        it for readmission.  Restore is page-exact for swap mode, so
        greedy tokens are unchanged through a preempt cycle."""
        req = pool.reqs[slot]
        ctx = len(req.prompt) + len(req.tokens)
        mode = ("recompute" if self.preemption == "recompute"
                or ctx <= self.recompute_below else "swap")
        m = telemetry.metrics()
        if mode == "swap":
            with telemetry.span("page_swap", direction="out",
                                request_id=req.rid,
                                pages=len(req.pages)):
                idx = jnp.asarray(np.asarray(req.pages, np.int32))
                host = jax.device_get(
                    [leaf[idx] for leaf in self._pages])
                st = jax.device_get(
                    {k: v[slot] for k, v in pool.state.items()})
            req.swap = {"mode": "swap", "pool": pool, "pages": host,
                        "state": st, "ver": req.weights_ver}
            m.counter("serving_pages_swapped_total").inc(
                len(req.pages))
        else:
            req.swap = {"mode": "recompute", "pool": pool}
        pool.reqs[slot] = None
        # draft KV is recompute-class: never part of the swap plan
        self._draft_invalidate(pool, slot)
        # parked requests re-match the store at readmission; holding
        # pins while parked would block eviction for no reader
        self._prefix_unpin(req)
        swap_plan = req.swap  # _release_pages clears it
        self._release_pages(req, pool, slot)
        req.swap = swap_plan
        self._parked.append(req)
        m.counter("serving_preemptions_total", reason=reason).inc()
        telemetry.instant("preempt", bucket=pool.env, slot=slot,
                          request_id=req.rid, mode=mode)
        flight_recorder.record("preempt", request_id=req.rid,
                               bucket=pool.env, reason=reason,
                               mode=mode)

    def _reserve_pages(self, req: _Request, n: int) -> bool:
        """Allocate ``n`` pages for an arriving/readmitted request,
        preempting strictly-lower-priority live requests while the
        pool is short (quota shortfalls never preempt — freeing other
        tenants' pages cannot help)."""
        if not self._alloc.fits_quota(n, req.tenant):
            return False
        pids = self._alloc_pages(n, req.tenant)
        while pids is None and self.preemption != "none":
            victim = self._pick_victim(below=req.priority)
            if victim is None:
                return False
            self._preempt(*victim, reason="admission")
            pids = self._alloc_pages(n, req.tenant)
        if pids is None:
            return False
        req.pages = pids
        return True

    def _sweep_parked(self) -> list[dict]:
        """Deadline check for PARKED requests: a preempted request
        waiting for readmission expires exactly like a queued one
        (the pre-paging engine only checked queued and live)."""
        out = []
        if not self._parked:
            return out
        now = telemetry.now()
        m = telemetry.metrics()
        for req in list(self._parked):
            if req.deadline is not None and now > req.deadline:
                self._parked.remove(req)
                env = req.swap["pool"].env
                self._release_pages(req)
                m.counter("serving_shed_total", reason="deadline",
                          bucket=env).inc()
                out.append(self._finish_error(
                    req, "deadline_exceeded", env))
        return out

    def _readmit_parked(self, variables) -> list[dict]:
        """Readmission sweep: parked requests re-enter (highest
        priority first, FIFO within a class) when their pool has a
        free slot and the allocator can cover them.  Swap-mode
        restores are page-exact; a weight swap since preemption
        invalidates the saved KV exactly like the prefix store, so
        those requests recompute from prompt + generated tokens
        under the new weights instead."""
        out = []
        if not self._parked:
            return out
        m = telemetry.metrics()
        for req in sorted(self._parked,
                          key=lambda r: (-r.priority, r.submit_order)):
            pool = req.swap["pool"]
            slot = next(
                (s for s in range(pool.n_slots)
                 if pool.reqs[s] is None and s not in pool.prefilling),
                None)
            if slot is None:
                continue
            # the satellite deadline fix: re-check AT readmission too
            if (req.deadline is not None
                    and telemetry.now() > req.deadline):
                self._parked.remove(req)
                self._release_pages(req)
                m.counter("serving_shed_total", reason="deadline",
                          bucket=pool.env).inc()
                out.append(self._finish_error(
                    req, "deadline_exceeded", pool.env))
                continue
            mode = req.swap["mode"]
            if (mode == "swap"
                    and req.swap["ver"] != self._weights_ver):
                mode = "recompute"  # stale KV: invalidated like the
                #                     prefix store on weight swap
            if mode == "swap":
                n = len(req.swap["pages"][0])
            else:
                ext_len = len(req.prompt) + len(req.tokens)
                n = self._pages_needed(ext_len, pool)
            if not self._reserve_pages(req, n):
                continue  # stays parked; retried next sweep
            self._parked.remove(req)
            m.counter("serving_readmissions_total").inc()
            flight_recorder.record("readmit", request_id=req.rid,
                                   bucket=pool.env, mode=mode,
                                   pages=n)
            if mode == "swap":
                swap, req.swap = req.swap, None
                self._set_table_row(pool, slot, req.pages)
                with telemetry.span("page_swap", direction="in",
                                    request_id=req.rid, pages=n):
                    idx = jnp.asarray(
                        np.asarray(req.pages, np.int32))
                    self._pages = [
                        leaf.at[idx].set(jnp.asarray(h))
                        for leaf, h in zip(self._pages,
                                           swap["pages"])]
                    pool.state = {
                        k: v.at[slot].set(swap["state"][k])
                        for k, v in pool.state.items()}
                pool.reqs[slot] = req
                # the TARGET restore is page-exact; the draft cache
                # for this slot is whatever its last tenant left
                self._draft_invalidate(pool, slot)
            else:
                req.swap = None
                req.weights_ver = self._weights_ver
                # a request preempted past its envelope was rolling
                # over row env-1; recompute keeps the most recent
                # env tokens of the ledger (the rolled state is
                # unrecoverable by construction — swap mode
                # preserves it exactly)
                out.extend(self._prefill_whole(
                    pool, slot, req, variables,
                    prompt_override=req.ledger(pool.env)))
            self._note_gauges(pool)
        return out

    def _grow_pages(self, pool: _Pool) -> list[dict]:
        """Before a decode quantum, extend every live slot's table to
        cover the rows it will write (``pos + steps_per_sync``, capped
        at the envelope) — an uncovered write would scatter real K/V
        onto the garbage page and lose it.  Exhaustion preempts a
        strictly-lower-priority victim; if none exists the grower
        parks ITSELF (swap/recompute) — or, with preemption off, is
        shed with ``error="kv_pages_exhausted"``."""
        out = []
        page = self.page_size
        m = telemetry.metrics()
        for slot in range(pool.n_slots):
            req = pool.reqs[slot]
            if req is None or slot in pool.prefilling:
                continue
            # host mirror of the device pos: prompt + generated - 1
            # (the first generated token came from prefill and is
            # written at pos t_p by the next decode write); live
            # writes this quantum stop at the remaining budget, so
            # growth never demands more pages than submit() validated
            # against kv_pages/quota (dead re-writes past the budget
            # scatter to the garbage page — dead data, never read)
            pos = len(req.prompt) + max(0, len(req.tokens) - 1)
            live = min(self.steps_per_sync,
                       req.max_new - len(req.tokens))
            need = paging.pages_for(min(pool.env, pos + live), page)
            changed = False
            while len(req.pages) < need:
                blocked_quota = not self._alloc.fits_quota(
                    1, req.tenant)
                pids = (None if blocked_quota
                        else self._alloc_pages(1, req.tenant))
                if pids is not None:
                    req.pages.extend(pids)
                    changed = True
                    continue
                if not blocked_quota and self.preemption != "none":
                    victim = self._pick_victim(below=req.priority,
                                               exclude=req)
                    if victim is not None:
                        self._preempt(*victim, reason="growth")
                        continue
                if self.preemption == "none":
                    pool.reqs[slot] = None
                    self._release_pages(req, pool, slot)
                    m.counter("serving_shed_total",
                              reason="kv_pages", bucket=pool.env).inc()
                    out.append(self._finish_error(
                        req, "kv_pages_exhausted", pool.env))
                else:
                    # no lower-priority victim (or quota-blocked):
                    # park SELF until pages free up
                    self._preempt(pool, slot,
                                  reason=("quota" if blocked_quota
                                          else "growth"))
                changed = False
                break
            if changed:
                self._set_table_row(pool, slot, req.pages)
        return out

    def free_pages(self) -> Optional[int]:
        """Free device KV pages right now (``None``: envelope mode).
        Safe to read from any thread — the gateway's ``least_loaded``
        tie-break samples it."""
        return self._alloc.n_free if self._paged else None

    def paging_stats(self) -> dict:
        """Host-side paging/QoS counters (operator introspection; the
        same numbers feed the metrics registry)."""
        if not self._paged:
            return {"enabled": False}
        return {"enabled": True, "parked": len(self._parked),
                "preemption": self.preemption,
                **self._alloc.stats()}

    # ---- admission sweep ----------------------------------------------

    def _admit(self) -> list[dict]:
        finished = []
        # weights are snapshotted ONCE per admission sweep, so a
        # concurrent swap_variables takes effect at the next step
        # boundary, never mid-sweep
        variables = self.variables
        if self._paged:
            finished.extend(self._sweep_parked())
            finished.extend(self._readmit_parked(variables))
        for pool in self._pools:
            finished.extend(self._shed_expired_queued(pool))
            for slot in range(pool.n_slots):
                if pool.reqs[slot] is not None:
                    continue
                if self._paged:
                    req = self._pick_queued(pool)
                    if req is None:
                        break
                    if not self._reserve_pages(
                            req, self._pages_needed(len(req.prompt),
                                                    pool)):
                        with self._lock:  # wait at the head, in order
                            pool.queue.appendleft(req)
                        break
                else:
                    with self._lock:  # pop vs racing submit() appends
                        if not pool.queue:
                            break
                        req = pool.queue.popleft()
                admit = (self._admit_segmented if self._segmented
                         else self._prefill_whole)
                finished.extend(admit(pool, slot, req, variables))
            self._note_gauges(pool)
        return finished

    def _prefill_whole(self, pool: _Pool, slot: int, req: _Request,
                       variables, prompt_override=None) -> list[dict]:
        """The legacy one-shot prefill: one compiled program writes
        the whole padded prompt into the slot and installs its state
        (byte-identical behavior to the pre-prefix engine — the
        compile guard pins it).  ``prompt_override`` is the recompute
        readmission path: the "prompt" is the original prompt plus
        every token generated before preemption, and the budget
        accounting continues from where the request left off."""
        m = telemetry.metrics()
        self._draft_invalidate(pool, slot)  # new slot tenant
        prompt = (req.prompt if prompt_override is None
                  else prompt_override)
        t_p = len(prompt)
        t_pad = min(pool.env, _ceil_to(t_p, self.prefill_align))
        padded = np.full((1, t_pad), self.pad_id, np.int32)
        padded[0, :t_p] = prompt
        # generation budget left AFTER this prefill's sampled token
        n_left0 = req.max_new - len(req.tokens) - 1
        try:
            with telemetry.span("prefill", bucket=pool.env,
                                slot=slot, padded=t_pad,
                                request_id=req.rid):
                if self._paged:
                    self._set_table_row(pool, slot, req.pages)
                    (self._pages, pool.state,
                     tok0) = pool.prefill_fn(
                        variables, self._pages, pool.table,
                        pool.state, jnp.asarray(padded), slot,
                        t_p - 1, n_left0,
                        -1 if req.eos_id is None else req.eos_id,
                        self._next_rng())
                else:
                    pool.cache, pool.state, tok0 = pool.prefill_fn(
                        variables, pool.cache, pool.state,
                        jnp.asarray(padded), slot, t_p - 1,
                        n_left0,
                        -1 if req.eos_id is None else req.eos_id,
                        self._next_rng())
                req.tokens.append(int(tok0))
        except Exception as e:
            # Per-request error isolation: a poisoned request is
            # finished with an ``error`` result — its slot stays free
            # and its neighbors keep decoding — instead of the
            # exception killing step() for every slot.  (With buffer
            # donation on, a failure DURING execution can still
            # poison the pool; trace-/dispatch-time failures, the
            # common case, are fully isolated.)
            self._release_pages(req, pool, slot)
            return [self._finish_error(
                req, f"prefill_failed: {e!r}", pool.env)]
        req.t_first = req.t_first or telemetry.now()
        req.t_last_tok = telemetry.now()
        req.traces_seen = sum(self._traces.values())
        m.counter("serving_tokens_total", bucket=pool.env).inc()
        pool.reqs[slot] = req
        if (len(req.tokens) >= req.max_new
                or req.tokens[-1] == req.eos_id):
            return [self._finish(pool, slot)]
        return []

    def _admit_segmented(self, pool: _Pool, slot: int, req: _Request,
                         variables) -> list[dict]:
        """Prefix-cache + chunked admission: install the longest
        cached prefix by device copy, then plan the uncached tail as
        chunk programs (advanced by ``step()``, one per pool per
        call).  A fully uncached prompt with chunking off falls back
        to the legacy one-shot program — same compiled shapes, same
        admission latency."""
        m = telemetry.metrics()
        self._draft_invalidate(pool, slot)  # new slot tenant
        t_p = len(req.prompt)
        t_pad = min(pool.env, _ceil_to(t_p, self.prefill_align))
        align = self.prefill_align
        start = 0
        if self._prefix is not None:
            store = self._prefix
            path = store.match(req.prompt, (t_p - 1) // align)
            if path:
                start = len(path) * align
                try:
                    with telemetry.span("prefix_copy",
                                        bucket=pool.env, slot=slot,
                                        rows=start,
                                        request_id=req.rid):
                        for b, node in enumerate(path):
                            if self._paged:
                                # page == prefix block: install the
                                # segment into block b's own page
                                self._pages = self._page_copy_fn(
                                    self._pages, node.segments,
                                    req.pages[b])
                            else:
                                pool.cache = pool.copy_fn(
                                    pool.cache, node.segments, slot,
                                    b * align)
                except Exception as e:
                    self._release_pages(req, pool, slot)
                    return [self._finish_error(
                        req, f"prefill_failed: {e!r}", pool.env)]
                for node in path:   # pin: LRU must not evict under us
                    node.refs += 1
                req.prefix_path = tuple(path)
                store.hits += 1
                store.tokens_saved += start
                m.counter("serving_prefix_hits_total",
                          bucket=pool.env).inc()
                m.counter("serving_prefill_tokens_saved_total",
                          bucket=pool.env).inc(start)
            else:
                store.misses += 1
                m.counter("serving_prefix_misses_total",
                          bucket=pool.env).inc()
            m.gauge("serving_prefix_hit_rate").set(
                store.hits / (store.hits + store.misses))
        req.weights_ver = self._weights_ver
        if start == 0 and self.prefill_chunk is None:
            return self._prefill_whole(pool, slot, req, variables)
        padded = np.full((1, t_pad), self.pad_id, np.int32)
        padded[0, :t_p] = req.prompt
        quantum = self.prefill_chunk or (t_pad - start)
        chunks = []
        for c0 in range(start, t_pad, quantum):
            c1 = min(c0 + quantum, t_pad)
            final = c1 == t_pad
            # the true last token always lands in the final chunk
            # (t_p - 1 >= t_pad - align >= its start); non-final
            # chunks take any in-range row — their logits are unused
            last_rel = (t_p - 1 - c0) if final else (c1 - c0 - 1)
            chunks.append((c0, padded[:, c0:c1], last_rel, final))
        pool.reqs[slot] = req
        if self._paged:  # chunk writes must be page-backed from chunk 0
            self._set_table_row(pool, slot, req.pages)
        pool.prefilling[slot] = {"req": req, "chunks": chunks,
                                 "next": 0}
        if self.prefill_chunk is None:
            # prefix-only mode: the single tail program runs NOW, so
            # admission latency matches the legacy path
            return self._advance_prefill(pool, slot, variables)
        return []

    def _advance_prefill(self, pool: _Pool, slot: int,
                         variables) -> list[dict]:
        """Run ONE pending prefill chunk for ``slot``.  The request's
        deadline is re-checked first — between chunks, not only in
        ``_shed_expired_queued`` — so a chunked long prompt cannot
        ride out its own deadline mid-prefill."""
        plan = pool.prefilling[slot]
        req = plan["req"]
        m = telemetry.metrics()
        if req.deadline is not None and telemetry.now() > req.deadline:
            pool.reqs[slot] = None
            del pool.prefilling[slot]
            self._release_pages(req, pool, slot)
            m.counter("serving_shed_total", reason="deadline",
                      bucket=pool.env).inc()
            telemetry.instant("evict", bucket=pool.env, slot=slot,
                              request_id=req.rid)
            return [self._finish_error(req, "deadline_exceeded",
                                       pool.env)]
        c0, chunk, last_rel, final = plan["chunks"][plan["next"]]
        try:
            with telemetry.span("prefill_chunk", bucket=pool.env,
                                slot=slot, start=c0,
                                size=chunk.shape[1], final=final,
                                request_id=req.rid):
                if self._paged:
                    self._pages, pool.state, tok0 = pool.chunk_fn(
                        variables, self._pages, pool.table,
                        pool.state, jnp.asarray(chunk), slot, c0,
                        last_rel, final, req.max_new - 1,
                        -1 if req.eos_id is None else req.eos_id,
                        self._next_rng())
                else:
                    pool.cache, pool.state, tok0 = pool.chunk_fn(
                        variables, pool.cache, pool.state,
                        jnp.asarray(chunk), slot, c0, last_rel, final,
                        req.max_new - 1,
                        -1 if req.eos_id is None else req.eos_id,
                        self._next_rng())
                if final:
                    req.tokens.append(int(tok0))
        except Exception as e:
            # same per-request isolation contract as _prefill_whole
            pool.reqs[slot] = None
            del pool.prefilling[slot]
            self._release_pages(req, pool, slot)
            return [self._finish_error(
                req, f"prefill_failed: {e!r}", pool.env)]
        plan["next"] += 1
        if not final:
            return []
        del pool.prefilling[slot]
        req.t_first = telemetry.now()
        req.t_last_tok = req.t_first
        req.traces_seen = sum(self._traces.values())
        m.counter("serving_tokens_total", bucket=pool.env).inc()
        if req.max_new == 1 or req.tokens[-1] == req.eos_id:
            return [self._finish(pool, slot)]
        return []

    def _prefix_unpin(self, req: _Request) -> None:
        """Release the request's live refs on its matched prefix path
        (idempotent: the path is cleared after the first call)."""
        for node in req.prefix_path:
            node.refs -= 1
        req.prefix_path = ()

    def _donate_prefix(self, pool: _Pool, slot: int,
                       req: _Request) -> None:
        """Donate the finished request's prompt K/V back to the store:
        extract each whole ``prefill_align`` block not already cached
        as envelope-free device segments, then evict down to the LRU
        byte budget.  Best-effort — a failure here must never fail the
        request it rides on."""
        store = self._prefix
        align = self.prefill_align
        n = min(len(req.prompt) // align, pool.env // align)
        if self._paged:
            # page_size == prefill_align (enforced in __init__), so
            # block b of the prompt lives exactly in req.pages[b] —
            # donation is a page slice, no envelope extraction
            n = min(n, len(req.pages))
        inserted = False
        try:
            node = store.root
            for b in range(n):
                key = req.prompt[b * align:(b + 1) * align].tobytes()
                child = node.children.get(key)
                if child is None:
                    if self._paged:
                        segs = self._page_extract_fn(
                            self._pages, req.pages[b])
                    else:
                        segs = pool.extract_fn(pool.cache, slot,
                                               b * align)
                    child = store.insert(node, key, segs)
                    inserted = True
                else:
                    store._touch(child)
                node = child
        except Exception:
            return
        if inserted:
            evicted = store.evict_to_budget()
            if evicted:
                telemetry.metrics().counter(
                    "serving_prefix_evictions_total").inc(evicted)

    def prefix_stats(self) -> dict:
        """Host-side prefix-store counters (operator introspection;
        the same numbers feed the metrics registry)."""
        if self._prefix is None:
            return {"enabled": False}
        s = self._prefix
        return {"enabled": True, "hits": s.hits, "misses": s.misses,
                "evictions": s.evictions,
                "invalidations": s.invalidations,
                "tokens_saved": s.tokens_saved, "nodes": s.n_nodes,
                "bytes": s.nbytes, "budget_bytes": s.budget}

    # ---- disaggregated prefill/decode interchange ---------------------
    #
    # The store mutators below follow the store's ownership discipline:
    # call them from the stepping thread only (the gateway replica
    # serializes them through its command mailbox, which IS the
    # stepping thread).

    def match_blocks(self, prompt) -> int:
        """How many leading whole ``prefill_align`` blocks of
        ``prompt`` the local prefix store already holds — the cluster-
        tier probe a decode-side router runs before asking the prefill
        pool's store (and before recomputing)."""
        if self._prefix is None:
            return 0
        prompt = np.ascontiguousarray(prompt, np.int32)
        return len(self._prefix.match(
            prompt, len(prompt) // self.prefill_align))

    def export_prefix(self, prompt) -> Optional[dict]:
        """Pull ``prompt``'s cached prefix blocks out of the store as
        HOST arrays — the prefill side of the disaggregated handoff.
        Returns ``{"prompt", "n_blocks", "weights_ver", "blocks"}``
        (``blocks[b]`` = block ``b``'s segment leaves, outermost
        first in cache-flatten order) or ``None`` when nothing is
        cached.  Pairs with ``pack_kv_blocks`` for the wire."""
        if self._prefix is None:
            return None
        prompt = np.ascontiguousarray(prompt, np.int32)
        path = self._prefix.match(
            prompt, len(prompt) // self.prefill_align)
        if not path:
            return None
        blocks = [[np.asarray(jax.device_get(s)) for s in n.segments]
                  for n in path]
        return {"prompt": prompt, "n_blocks": len(blocks),
                "weights_ver": self._weights_ver, "blocks": blocks}

    def import_prefix(self, prompt, blocks,
                      weights_ver: Optional[int] = None) -> int:
        """Install a shipped block set into the local prefix store —
        the decode side of the handoff.  Admission then takes the
        ordinary prefix-hit path (device copy + tail prefill), which
        existing parity tests pin byte-identical to a monolithic
        engine, so imported KV changes WHERE prefill ran, never what
        tokens come out.  Returns the number of blocks newly
        installed (already-cached blocks are touched, not
        duplicated).  A ``weights_ver`` that does not match the local
        engine's is a STALE export — rejected whole (return 0): KV
        under different weights is silently wrong."""
        if self._prefix is None or not blocks:
            return 0
        if weights_ver is not None and weights_ver != self._weights_ver:
            return 0
        store = self._prefix
        prompt = np.ascontiguousarray(prompt, np.int32)
        align = self.prefill_align
        installed = 0
        node = store.root
        for b, segs in enumerate(blocks):
            key = prompt[b * align:(b + 1) * align].tobytes()
            if len(key) < align * 4:
                break  # ragged tail: never index a partial block
            child = node.children.get(key)
            if child is None:
                child = store.insert(
                    node, key, [jnp.asarray(s) for s in segs])
                installed += 1
            else:
                store._touch(child)
            node = child
        if installed:
            evicted = store.evict_to_budget()
            if evicted:
                telemetry.metrics().counter(
                    "serving_prefix_evictions_total").inc(evicted)
        return installed

    def _finish(self, pool: _Pool, slot: int) -> dict:
        """Evict the finished request and assemble its result dict.

        Timing fields (all from ``telemetry.now()``, the repo's single
        monotonic clock — differences are meaningful, absolute values
        are not):

        * ``t_submit`` — when ``submit()`` queued the request;
        * ``t_first``  — when its first token materialized on the host
          (prefill return), i.e. queue-to-first-token is
          ``ttft = t_first - t_submit``;
        * ``t_finish`` — when the finished request was evicted;
          completion latency is ``latency = t_finish - t_submit``.

        The derived ``ttft``/``latency`` keys ride along precomputed.
        Engine-owned keys (including the timing fields above) win over
        same-named meta keys — ordered delivery depends on
        ``request_id`` surviving."""
        req = pool.reqs[slot]
        pool.reqs[slot] = None
        self._inflight.discard(req.rid)
        # unpin FIRST so this request's own path is evictable (but
        # freshly touched) when its donation pushes over budget
        self._prefix_unpin(req)
        if (self._prefix is not None
                and req.weights_ver == self._weights_ver):
            # rows [0, t_p) still hold the prompt's K/V — decode only
            # appended at pos >= t_p — so the slot is donated before
            # the result is assembled.  A weights_ver mismatch means
            # a swap landed mid-request: its KV is hybrid, never
            # donated.
            self._donate_prefix(pool, slot, req)
        # pages go back to the free list AFTER donation — the extract
        # above reads them; freeing never touches device page contents
        # (page data is only overwritten when a new owner writes it)
        self._release_pages(req, pool, slot)
        t_finish = telemetry.now()
        ttft = req.t_first - req.t_submit
        latency = t_finish - req.t_submit
        m = telemetry.metrics()
        m.counter("serving_finished_total", bucket=pool.env).inc()
        m.histogram("serving_ttft_seconds").observe(ttft)
        m.histogram("serving_latency_seconds").observe(latency)
        telemetry.instant("evict", bucket=pool.env, slot=slot,
                          request_id=req.rid)
        return {**req.meta,
                "request_id": req.rid, "prompt": req.prompt,
                "tokens": np.asarray(req.tokens, np.int32),
                "t_submit": req.t_submit, "t_first": req.t_first,
                "t_finish": t_finish, "ttft": ttft,
                "latency": latency}

    def _finish_error(self, req: _Request, error: str,
                      env: int) -> dict:
        """Terminal ERROR result: same shape as ``_finish``'s dict plus
        an ``error`` key (never present on success); ``tokens`` holds
        whatever was generated before the failure, ``ttft`` is None for
        a request that never produced a token.  The request has already
        left its queue/slot."""
        self._inflight.discard(req.rid)
        self._prefix_unpin(req)
        self._release_pages(req)  # safety net: idempotent, no table
        t_finish = telemetry.now()
        m = telemetry.metrics()
        m.counter("serving_request_errors_total", bucket=env).inc()
        telemetry.instant("request_error", bucket=env,
                          request_id=req.rid, error=error)
        # one durable event per terminal error result — covers
        # deadline expiries, poisoned prefills, and engine_closed
        # cancellations through the single exit point they share
        flight_recorder.record("request_error", request_id=req.rid,
                               bucket=env, error=error)
        ttft = (None if req.t_first is None
                else req.t_first - req.t_submit)
        return {**req.meta,
                "request_id": req.rid, "prompt": req.prompt,
                "tokens": np.asarray(req.tokens, np.int32),
                "error": error,
                "t_submit": req.t_submit, "t_first": req.t_first,
                "t_finish": t_finish, "ttft": ttft,
                "latency": t_finish - req.t_submit}

    def _note_inter_token(self, req: _Request, n: int) -> None:
        """Observe the decode-side inter-token gap for ``n`` freshly
        committed tokens: elapsed time since the request's previous
        token, spread evenly over the batch (speculative commits land
        several tokens from one program).  Feeds
        ``serving_inter_token_seconds`` — the histogram behind the
        ``inter_token_p99`` SLO signal and the disaggregation A/B's
        flood-flatness gate."""
        if n <= 0:
            return
        t_now = telemetry.now()
        # a gap that spans a program trace is a compile stall (cold
        # engine, new shape), not decode cadence — recording it would
        # flip a freshly built engine's SLO verdict critical and make
        # rolling_update's health gate roll back a healthy swap
        traces = sum(self._traces.values())
        if req.t_last_tok is not None and traces == req.traces_seen:
            gap = (t_now - req.t_last_tok) / n
            h = telemetry.metrics().histogram(
                "serving_inter_token_seconds")
            for _ in range(n):
                h.observe(gap)
        req.t_last_tok = t_now
        req.traces_seen = traces

    # ---- speculative decode -------------------------------------------

    def _commit_tokens(self, req: _Request,
                       cand: list) -> tuple[int, bool]:
        """Append candidate tokens under the PER-TOKEN stop scan: the
        ``max_new`` clamp and the ``eos_id`` check apply to EVERY
        committed token — generation stops mid-window and the tail of
        an accepted run is discarded, exactly the rule the one-token
        step loop applies per step.  Returns ``(committed,
        finished)``."""
        c = 0
        fin = False
        for t in cand:
            req.tokens.append(int(t))
            c += 1
            if (len(req.tokens) >= req.max_new
                    or req.tokens[-1] == req.eos_id):
                fin = True
                break
        self._note_inter_token(req, c)
        return c, fin

    def _spec_grow(self, pool: _Pool, slot: int, req: _Request,
                   start: int, width: int) -> bool:
        """Cover rows ``[0, start + width)`` before a WIDE verify.
        The widening allocation is opportunistic — no preemption: a
        shortage (pool or tenant quota) falls back to the single-
        token verify, whose one write row standard ``_grow_pages``
        growth already covered, so speculation degrades to baseline
        throughput instead of evicting a neighbor."""
        need = paging.pages_for(min(pool.env, start + width),
                                self.page_size)
        extra = need - len(req.pages)
        if extra <= 0:
            return True
        if not self._alloc.fits_quota(extra, req.tenant):
            return False
        pids = self._alloc_pages(extra, req.tenant)
        if pids is None:
            return False
        req.pages.extend(pids)
        self._set_table_row(pool, slot, req.pages)
        return True

    def _spec_rewind(self, pool: _Pool, slot: int, req: _Request,
                     pos_next: int) -> int:
        """Roll rejected speculation back in the PAGE TABLE: pages
        past the committed frontier (``pos_next`` is the next write
        row, so ``pos_next + 1`` rows stay covered) return to the
        allocator and their table entries to the garbage page.  The
        padded-prompt floor is kept — prefix donation slices prompt
        pages at finish — and freed pages may be re-earned by a later
        ``_spec_grow``, always within the worst case ``submit()``
        validated."""
        t_pad = min(pool.env,
                    _ceil_to(len(req.prompt), self.prefill_align))
        keep = max(
            paging.pages_for(min(pool.env, pos_next + 1),
                             self.page_size),
            paging.pages_for(t_pad, self.page_size))
        if len(req.pages) <= keep:
            return 0
        drop = req.pages[keep:]
        del req.pages[keep:]
        self._alloc.free(drop, req.tenant)
        telemetry.metrics().counter(
            "serving_pages_freed_total").inc(len(drop))
        self._set_table_row(pool, slot, req.pages)
        return len(drop)

    def _draft_invalidate(self, pool: _Pool, slot: int) -> None:
        """Mark one slot's draft cache stale (rebuild-from-ledger at
        the next propose).  Draft KV is always recompute-class: slot
        turnover, preemption, swap-mode restore, and weight swaps all
        land here instead of any host round-trip."""
        if pool.spec is not None and "dpos" in pool.spec:
            pool.spec["dpos"][slot] = -1

    def _draft_propose(self, pool: _Pool, variables,
                       elig: dict) -> dict:
        """Draft-model proposals for every eligible slot: first
        rebuild any invalidated slot's draft cache from its token
        ledger (one bounded-shape prefill — the recompute-class
        contract), then ONE batched compiled program runs ``k + 1``
        cached greedy draft steps for all slots at once.  Returns
        ``{slot: k proposals}`` for the slots that were drafted."""
        d = pool.spec
        dvars = self._spec["draft_variables"]
        k = self._spec["k"]
        for s, ok in sorted(elig.items()):
            if not ok or d["dpos"][s] >= 0:
                continue
            req = pool.reqs[s]
            ledger = req.ledger(pool.env)
            if len(ledger) >= 2:
                t_pad = min(pool.env,
                            _ceil_to(len(ledger) - 1,
                                     self.prefill_align))
                padded = np.full((1, t_pad), self.pad_id, np.int32)
                padded[0, :len(ledger) - 1] = ledger[:-1]
                with telemetry.span("draft_prefill", bucket=pool.env,
                                    slot=s, padded=t_pad,
                                    request_id=req.rid):
                    d["cache"] = d["prefill_fn"](
                        dvars, d["cache"], jnp.asarray(padded), s)
            d["dpos"][s] = len(ledger) - 1
            d["dtok"][s] = ledger[-1]
        live = np.array([bool(elig.get(s)) and d["dpos"][s] >= 0
                         for s in range(pool.n_slots)])
        if not live.any():
            return {}
        with telemetry.span("draft_step", bucket=pool.env, k=k):
            d["cache"], props = d["propose_fn"](
                dvars, d["cache"], jnp.asarray(d["dtok"]),
                jnp.asarray(d["dpos"]), jnp.asarray(live))
            props = np.asarray(props)
        return {s: props[:, s] for s in range(pool.n_slots)
                if live[s]}

    def _spec_decode(self, pool: _Pool, variables) -> list[dict]:
        """One speculative decode quantum for a pool — the spec-mode
        replacement for the batched step dispatch.  Per live slot:
        propose up to ``k`` tokens (n-gram ledger lookup or the
        batched draft program), verify the whole window in one dense
        pass, commit the longest accepted prefix plus the bonus token
        under the per-token stop scan, and roll the rejected tail
        back (position rewind; paged mode also returns tail pages).
        A slot with no proposal (or out of budget/pages, or opted
        out) runs the single-token verify — byte-identical to the
        baseline step for that slot."""
        spec = self._spec
        k = spec["k"]
        m = telemetry.metrics()
        finished: list[dict] = []
        slots = [s for s, r in enumerate(pool.reqs)
                 if r is not None and s not in pool.prefilling]
        if not slots:
            return finished
        # WIDE-verify eligibility: the whole k+1 window must fit the
        # remaining budget — which, with the routing invariant
        # t_p + max_new <= env, also bounds every row the verify and
        # draft programs write to env - 2 (no envelope overflow, no
        # page demand past what submit() validated)
        elig = {s: (pool.reqs[s].spec_on is not False
                    and pool.reqs[s].max_new
                    - len(pool.reqs[s].tokens) > k)
                for s in slots}
        props: dict = {}
        if spec["draft_model"] is not None and any(elig.values()):
            props = self._draft_propose(pool, variables, elig)
        n_tok = 0
        for s in slots:
            req = pool.reqs[s]
            ledger = req.ledger(pool.env)
            start = len(ledger) - 1
            p = np.empty((0,), np.int32)
            if elig[s]:
                if spec["draft_model"] is None:
                    p = _speculative.ngram_propose(ledger, k,
                                                   spec["ngram"])
                else:
                    p = props.get(s, p)
            width = k + 1 if len(p) else 1
            if (width > 1 and self._paged
                    and not self._spec_grow(pool, s, req, start,
                                            width)):
                p = p[:0]  # page-short: degrade to the 1-wide verify
                width = 1
            chunk = np.full((1, width), self.pad_id, np.int32)
            chunk[0, 0] = ledger[-1]
            chunk[0, 1:1 + len(p)] = p
            try:
                with telemetry.span("verify", bucket=pool.env,
                                    slot=s, width=width,
                                    request_id=req.rid):
                    vf = pool.spec["verify_fns"][width]
                    if self._paged:
                        self._pages, greedy = vf(
                            variables, self._pages, pool.table,
                            jnp.asarray(chunk), s, start)
                    else:
                        pool.cache, greedy = vf(
                            variables, pool.cache,
                            jnp.asarray(chunk), s, start)
                    greedy = np.asarray(greedy)
            except Exception as e:
                # same per-request isolation contract as prefill
                pool.reqs[s] = None
                self._release_pages(req, pool, s)
                finished.append(self._finish_error(
                    req, f"verify_failed: {e!r}", pool.env))
                continue
            n = _speculative.accept_length(p, greedy)
            c, fin = self._commit_tokens(
                req, [int(x) for x in p[:n]] + [int(greedy[n])])
            n_tok += c
            if len(p):
                self._spec_proposed += len(p)
                self._spec_accepted += n
                m.counter("serving_spec_proposed_total",
                          bucket=pool.env).inc(len(p))
                m.counter("serving_spec_accepted_total",
                          bucket=pool.env).inc(n)
                m.histogram("serving_spec_accept_len").observe(n)
                m.gauge("serving_spec_accept_rate").set(
                    self._spec_accepted
                    / max(self._spec_proposed, 1))
                rejected = len(p) - n
                if rejected:
                    freed = (0 if fin or not self._paged
                             else self._spec_rewind(pool, s, req,
                                                    start + c))
                    flight_recorder.record(
                        "spec_rollback", request_id=req.rid,
                        bucket=pool.env, rejected=rejected,
                        pages_freed=freed)
            if spec["draft_model"] is not None and not fin:
                # commit keeps the draft exactly one token behind the
                # ledger (the k+1-step propose wrote every accepted
                # row's draft K/V), so only the host mirrors move
                pool.spec["dpos"][s] = start + c
                pool.spec["dtok"][s] = req.tokens[-1]
            if fin:
                finished.append(self._finish(pool, s))
        if n_tok:
            m.counter("serving_tokens_total",
                      bucket=pool.env).inc(n_tok)
        return finished

    def spec_stats(self) -> dict:
        """Host-side speculative-decoding counters (operator
        introspection; the same numbers feed the metrics registry
        and the ``spec_accept_rate`` SLO signal)."""
        if self._spec is None:
            return {"enabled": False}
        p, a = self._spec_proposed, self._spec_accepted
        return {"enabled": True,
                "proposer": self._spec["proposer"],
                "k": self._spec["k"], "proposed": p, "accepted": a,
                "accept_rate": (a / p) if p else None}

    # ---- serving loop -------------------------------------------------

    def has_work(self) -> bool:
        return (any(p.live() or p.queue for p in self._pools)
                or bool(self._parked))

    def load(self) -> dict:
        """Occupancy snapshot for load hooks (the traffic simulator's
        per-replica observable): queued admissions, live slots, and
        preempted-parked requests."""
        with self._lock:
            return {"queued": sum(len(p.queue) for p in self._pools),
                    "live": sum(1 for p in self._pools
                                for r in p.reqs if r is not None),
                    "parked": len(self._parked)}

    def step(self) -> list[dict]:
        """Admit waiting requests into free slots, advance every live
        bucket by ``steps_per_sync`` tokens, evict newly finished
        requests and return their results (as-completed order).
        Deadline-expired requests (queued or live) come back as
        ``error`` results; a poisoned request errors out alone without
        stalling its neighbors' slots."""
        if self._closed:
            raise RuntimeError("engine is closed; step after close()")
        finished = self._admit()
        m = telemetry.metrics()
        # one weights snapshot per step: a concurrent swap_variables
        # lands atomically at the next step boundary (see _admit)
        variables = self.variables
        for pool in self._pools:
            # chunked-prefill interleave: at most ONE chunk per pool
            # per step, so a live slot's inter-token gap is bounded by
            # one chunk program (+ one decode quantum), never the full
            # prompt length
            if pool.prefilling:
                slot = next(iter(pool.prefilling))
                finished.extend(
                    self._advance_prefill(pool, slot, variables))
            if self._paged:
                # coverage invariant: before dispatch every live slot's
                # pages must cover its position plus this quantum's
                # writes — grow (preempting/parking as needed) NOW
                finished.extend(self._grow_pages(pool))
            if not pool.decodable():
                continue
            if self._spec is not None:
                # speculative mode replaces the batched one-token
                # dispatch with per-slot propose + verify (commits up
                # to k+1 tokens per slot per step); the deadline
                # sweep below is shared, so expiry mid-verify still
                # frees the slot this same step
                finished.extend(self._spec_decode(pool, variables))
            else:
                # the span covers dispatch AND the host sync
                # (np.asarray), so its duration is the true
                # step-quantum latency
                with telemetry.span("decode_step", bucket=pool.env,
                                    steps=self.steps_per_sync):
                    if self._paged:
                        (self._pages, pool.state, toks,
                         was_done) = pool.step_fn(
                            variables, self._pages, pool.table,
                            pool.state, self._next_rng())
                    else:
                        (pool.cache, pool.state, toks,
                         was_done) = pool.step_fn(
                            variables, pool.cache, pool.state,
                            self._next_rng())
                    toks = np.asarray(toks)
                    was_done = np.asarray(was_done)
                n_tok = 0
                for slot, req in enumerate(pool.reqs):
                    if req is None:
                        continue
                    got = 0
                    fin = False
                    for k in range(toks.shape[0]):
                        if was_done[k, slot]:
                            break
                        req.tokens.append(int(toks[k, slot]))
                        got += 1
                        if (len(req.tokens) >= req.max_new
                                or req.tokens[-1] == req.eos_id):
                            fin = True
                            break
                    if got:
                        self._note_inter_token(req, got)
                        n_tok += got
                    if fin:
                        finished.append(self._finish(pool, slot))
                if n_tok:
                    m.counter("serving_tokens_total",
                              bucket=pool.env).inc(n_tok)
            # live requests past their deadline free the slot NOW —
            # graceful degradation under a stuck/slow decode rather
            # than holding capacity for an answer nobody will take
            now = telemetry.now()
            for slot, req in enumerate(pool.reqs):
                if (req is not None and req.deadline is not None
                        and now > req.deadline):
                    pool.reqs[slot] = None
                    pool.prefilling.pop(slot, None)
                    self._release_pages(req, pool, slot)
                    m.counter("serving_shed_total", reason="deadline",
                              bucket=pool.env).inc()
                    telemetry.instant("evict", bucket=pool.env,
                                      slot=slot, request_id=req.rid)
                    finished.append(self._finish_error(
                        req, "deadline_exceeded", pool.env))
            self._note_gauges(pool)
        finished.extend(self._admit())
        return finished

    # ---- graceful shutdown --------------------------------------------

    def drain(self) -> list[dict]:
        """Serve everything in flight to completion and return ALL
        results (as-completed order) — queued requests included.  The
        graceful half of shutdown: ``drain()`` then ``close()``."""
        out = []
        while self.has_work():
            out.extend(self.step())
        return out

    def close(self) -> list[dict]:
        """Shut the engine down: requests still queued or mid-decode
        are CANCELLED (returned as ``error="engine_closed"`` results —
        every in-flight id is accounted for, nothing vanishes), the
        device cache pools are released, and further ``submit``/
        ``step`` calls raise.  Call ``drain()`` first for a graceful
        shutdown that finishes the backlog instead."""
        with self._lock:
            if self._closed:
                return []
            out = []
            for pool in self._pools:
                while pool.queue:
                    out.append(self._finish_error(
                        pool.queue.popleft(), "engine_closed",
                        pool.env))
                for slot, req in enumerate(pool.reqs):
                    if req is not None:
                        pool.reqs[slot] = None
                        out.append(self._finish_error(
                            req, "engine_closed", pool.env))
                pool.prefilling.clear()
                pool.cache = pool.state = None  # release the pool
                pool.spec = None  # draft cache + verify programs too
                if self._paged:
                    pool.table = pool.table_np = None
                self._note_gauges(pool)
            for req in self._parked:  # preempted requests too
                env = req.swap["pool"].env if req.swap else 0
                out.append(self._finish_error(req, "engine_closed",
                                              env))
            self._parked.clear()
            self._pages = None  # release the page pool
            if self._prefix is not None:
                self._prefix.clear()  # release device segments
            self._closed = True
        flight_recorder.record("engine_closed", cancelled=len(out))
        flight_recorder.flush()
        return out

    def health(self) -> dict:
        """SLO verdict over the active metrics registry — the same
        evaluation ``/healthz`` serves (``ok``/``degraded``/
        ``critical`` with per-signal breaches)."""
        return telemetry.metrics().health()

    def __enter__(self) -> "DecodeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _submit_item(self, item):
        """``run``'s item contract: a prompt array, or a mapping with
        ``"prompt"`` (+ optional ``"max_new_tokens"``/``"eos_id"``;
        other keys ride into the result as meta)."""
        if isinstance(item, Mapping):
            meta = {k: v for k, v in item.items()
                    if k not in ("prompt", "max_new_tokens",
                                 "eos_id", "tenant", "priority",
                                 "speculative")}
            return self.submit(
                item["prompt"],
                max_new_tokens=item.get("max_new_tokens"),
                eos_id=item.get("eos_id", _UNSET),
                tenant=item.get("tenant"),
                priority=item.get("priority", 1),
                speculative=item.get("speculative"), meta=meta)
        return self.submit(item)

    def run(self, requests: Iterable, *, ordered: bool = True
            ) -> Iterator[dict]:
        """Serve an iterable of requests to completion.

        Each item is a prompt array or a mapping with ``"prompt"``
        (+ optional ``"max_new_tokens"``/``"eos_id"``; other keys are
        carried into the result).  ``ordered=True`` yields results in
        submission order; ``False`` yields as completed (lower
        latency for early finishers).

        With ``queue_bound`` set, a mid-iterable ``ShedError`` is
        handled as BACKPRESSURE, not failure: submission pauses while
        the engine steps (freeing queue space), then resumes — so
        already-completed results are delivered, never discarded, and
        deadline/poison casualties come back as ``error`` rows —
        matching ``StreamingGenerator``'s backpressure contract.  The
        whole iterable is always accounted for: one result per item.
        """
        order: list = []
        buffered: dict = {}
        next_emit = 0
        stalled = None  # item shed at the door, awaiting capacity
        it = iter(requests)
        exhausted = False
        while True:
            # feed until a shed: ShedError here is backpressure — the
            # stalled item waits while step() drains the queue
            while not exhausted or stalled is not None:
                if stalled is None:
                    try:
                        stalled = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                try:
                    order.append(self._submit_item(stalled))
                    stalled = None
                except ShedError:
                    break
            if not self.has_work():
                if exhausted and stalled is None:
                    break
                # queue_bound >= 1 guarantees an idle engine admits:
                # a shed here means another consumer drained our work
                raise RuntimeError(
                    "run(): request shed while the engine is idle — "
                    "the engine is being stepped/drained concurrently")
            for res in self.step():
                if not ordered:
                    yield res
                    continue
                buffered[res["request_id"]] = res
                while (next_emit < len(order)
                       and order[next_emit] in buffered):
                    yield buffered.pop(order[next_emit])
                    next_emit += 1
        if ordered:
            while next_emit < len(order):
                yield buffered.pop(order[next_emit])
                next_emit += 1

    @property
    def compile_counts(self) -> dict:
        """{(kind, bucket[, padded_len]): trace count} — each compiled
        program traces exactly once, so steady-state serving holds
        these constant across ragged arrivals (the §23 bounded-
        program-set claim; pinned by the tier-1 compile guard)."""
        return dict(self._traces)

"""Serving gateway — multi-replica routing, failover, and rolling
weight updates in front of N ``DecodeEngine``s.

``DecodeEngine`` is deliberately single-driver: one thread steps the
compiled programs, and the engine's own lock only makes ``submit``
safe, not ``step``.  That leaves three production gaps this module
closes (the serving-side mirror of what ``ResilientPSClient`` /
``PSServer.restart_from`` already give the training side):

* **Routing** — ``ServingGateway`` spreads requests over K replicas
  under a pluggable policy: ``round_robin`` (fair under uniform
  traffic), ``least_loaded`` (queue-depth + slot-occupancy aware,
  breaking ties on the paged engines' ``free_pages`` headroom so
  paged replicas absorb bursts first — envelope replicas fall back
  to queue depth alone; the right default under ragged decode
  lengths), or ``session`` (sticky key-hash affinity, so a
  conversation keeps hitting the replica that holds its KV prefix
  warm).
* **Failover** — a replica erroring, shedding, or dying mid-stream
  does not fail the request: the gateway reschedules it onto another
  replica under the same seeded full-jitter backoff discipline as
  ``ResilientPSClient``, and first-completion-wins futures make
  delivery exactly-once even when a timed-out attempt later limps
  home.  Each engine's in-flight ``request_id`` dedupe keeps a single
  engine at-most-once; a killed replica's in-flight requests complete
  elsewhere (the chaos test pins this).
* **Rolling weight updates** — ``rolling_update(source)`` pulls new
  weights from a live parameter server (``HostParameterServer`` /
  ``ShardedParameterServer`` / a PS client), a PS snapshot file
  (``checkpoint.ps_snapshot_center``), or a raw pytree, then drains
  and hot-swaps ONE replica at a time (``DecodeEngine.
  swap_variables`` — same treedef/shapes, zero recompiles) while the
  others keep serving.  After each swap the replica's health is
  re-checked; a ``critical`` verdict rolls every already-updated
  replica back to the pre-rollout weights.

Replica arms:

* ``EngineReplica`` — in-process: wraps one engine with its own
  driver thread and a mailbox, so submission is thread-safe by
  construction and weight swaps land exactly at step boundaries.
* ``ReplicaServer`` / ``RemoteReplica`` — the socket arm: the same
  replica served over ``parallel.transport`` framing (msgpack
  payloads via ``pack_obj``, never pickle), with ``trace_header()``
  propagation so gateway→replica spans pair up in a merged Perfetto
  timeline, and the ``parallel.faults.ChaosTransport`` choke point in
  the path (``target_ports={replica_port}`` attacks just this hop).
  ``ReplicaServer.kill()`` severs the wire AND the driver — the crash
  the failover machinery exists for.

Observability: ``gateway_requests_total{replica,policy}`` /
``gateway_failovers_total{replica}`` counters (their ratio is the
watchdog's ``failover_rate`` signal), swap/rollout spans, and flight-
recorder events — ``replica_down``, ``failover``, ``weight_swap``,
``rollback`` — so a postmortem can replay a rollout or a crash story
from disk.  ``healthz()`` aggregates per-replica verdicts into one
gateway state.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import queue
import socket
import threading
import zlib
from typing import Any, Callable, Iterable, Iterator, Mapping, Optional

import jax
import numpy as np

from distkeras_tpu import flight_recorder, paging, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.parallel import transport
from distkeras_tpu.serving import (ShedError, pack_kv_blocks,
                                   unpack_kv_blocks)

_UNSET = object()

POLICIES = ("round_robin", "least_loaded", "session", "prefix")


class ReplicaDown(ConnectionError):
    """The addressed replica is dead (driver crashed, socket severed,
    or stopped) — the gateway's cue to fail the attempt over.  A
    ``ConnectionError`` subclass so transport-level and replica-level
    failures share one retry classification."""


class _Future:
    """First-completion-wins result cell: ``set`` returns True only
    for the first caller, so a late duplicate (a timed-out attempt
    completing after its failover already won) is dropped — delivery
    is exactly-once even when execution was not."""

    __slots__ = ("_lock", "_event", "_result", "_set")

    def __init__(self):
        self._lock = racecheck.lock("gateway.future")
        self._event = threading.Event()
        self._result = None
        self._set = False

    def set(self, result) -> bool:
        with self._lock:
            if self._set:
                return False
            self._set = True
            self._result = result
        self._event.set()
        return True

    def ready(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        return self._result


# ---------------------------------------------------------------------
# in-process replica: one engine, one driver thread
# ---------------------------------------------------------------------


class EngineReplica:
    """One ``DecodeEngine`` plus its own driver thread.

    All interaction goes through a mailbox the driver consumes between
    step quanta: ``dispatch`` enqueues a request (callback-style
    completion), ``swap`` enqueues a weight swap (so it executes at a
    step boundary by construction — the driver never holds a step
    half-done), ``quiesce`` blocks until nothing is queued or live.
    The engine itself is never touched from another thread, which is
    exactly the threading contract ``DecodeEngine.step`` demands.

    A driver crash (poisoned engine, injected kill) marks the replica
    down, records a ``replica_down`` flight event, and fails every
    pending request with ``ReplicaDown`` — the gateway then reroutes
    them.  ``stop()`` is the graceful variant: in-flight requests come
    back as the engine's ``error="engine_closed"`` results (which the
    gateway also treats as failover-able, so stopping one replica for
    maintenance loses nothing).
    """

    def __init__(self, engine, name: str = "replica0"):
        self.engine = engine
        self.name = str(name)
        # RLock'd condition: load() re-enters from quiesce's wait loop
        self._cv = racecheck.condition("gateway.replica_cv")
        self._mailbox: collections.deque = collections.deque()
        self._pending: dict[Any, Callable] = {}
        self._alive = False  # guarded-by: _cv
        self._stop_req = False
        self._killed = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "EngineReplica":
        if self._thread is not None:
            return self
        with self._cv:  # health() may race the spawn below
            self._alive = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"dkt-replica-{self.name}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Graceful shutdown: the driver exits, the engine is closed,
        and in-flight requests are delivered as ``engine_closed``
        error results (never silently dropped)."""
        with self._cv:
            self._stop_req = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    def kill(self) -> None:
        """Crash simulation: the driver dies at its next loop top as
        if the process had — pending requests fail with
        ``ReplicaDown`` and the gateway's failover takes over."""
        with self._cv:
            self._killed = True
            self._cv.notify_all()

    @property
    def alive(self) -> bool:
        return self._alive

    # -- gateway-facing surface ---------------------------------------

    def load(self) -> int:
        """Requests owned by this replica (queued in the mailbox or in
        the engine) — the ``least_loaded`` routing signal."""
        with self._cv:
            return len(self._pending) + sum(
                1 for c in self._mailbox if c[0] == "submit")

    def free_pages(self) -> Optional[int]:
        """Free device KV pages on a paged engine (``None``: envelope
        pools) — ``least_loaded``'s tie-break signal."""
        fn = getattr(self.engine, "free_pages", None)
        return fn() if callable(fn) else None

    def dispatch(self, spec: Mapping, on_result: Callable) -> None:
        """Enqueue one request; ``on_result(result_or_exception)``
        fires exactly once from the driver thread."""
        with self._cv:
            if not self._alive:
                raise ReplicaDown(f"replica {self.name} is down")
            self._mailbox.append(("submit", dict(spec), on_result))
            self._cv.notify_all()

    def swap(self, variables: Mapping,
             timeout: float = 60.0) -> None:
        """Install new weights at the next step boundary (blocks until
        the driver has executed the swap); raises on mismatch."""
        fut = _Future()
        with self._cv:
            if not self._alive:
                raise ReplicaDown(f"replica {self.name} is down")
            self._mailbox.append(("swap", variables, fut.set))
            self._cv.notify_all()
        res = fut.wait(timeout)
        if isinstance(res, Exception):
            raise res

    def _kv_call(self, op: str, payload, timeout: float):
        """Run one prefix-store interchange op on the DRIVER thread
        (the store's ownership discipline — see ``DecodeEngine.
        export_prefix``) and block for its result."""
        fut = _Future()
        with self._cv:
            if not self._alive:
                raise ReplicaDown(f"replica {self.name} is down")
            self._mailbox.append(("kv", (op, payload), fut.set))
            self._cv.notify_all()
        res = fut.wait(timeout)
        if isinstance(res, Exception):
            raise res
        return res

    def kv_probe(self, prompt, timeout: float = 60.0) -> int:
        """Leading prompt blocks the engine's prefix store already
        holds (the router's ship-only-what's-missing check)."""
        return self._kv_call("probe", prompt, timeout)

    def kv_export(self, prompt, timeout: float = 60.0):
        """The engine's cached prefix blocks for ``prompt`` as a host
        export dict (``None``: nothing cached) — the prefill side of
        the disaggregated handoff."""
        return self._kv_call("export", prompt, timeout)

    def kv_import(self, export: Mapping,
                  timeout: float = 60.0) -> int:
        """Install a shipped block set into the engine's prefix store;
        returns blocks newly installed — the decode side of the
        handoff."""
        return self._kv_call("import", export, timeout)

    def variables(self) -> Mapping:
        """The engine's current weights (read-only use: the rollback
        snapshot).  Safe without the driver — ``swap_variables``
        replaces the whole dict atomically under the engine lock."""
        return self.engine.variables

    def quiesce(self, timeout: float = 60.0) -> None:
        """Block until the replica holds no work (the drain step of a
        rolling update — the gateway stops routing here first)."""
        deadline = telemetry.now() + timeout
        with self._cv:
            while self.load() > 0:
                left = deadline - telemetry.now()
                if left <= 0:
                    raise TimeoutError(
                        f"replica {self.name} did not quiesce within "
                        f"{timeout}s ({self.load()} in flight)")
                self._cv.wait(min(left, 0.1))

    def health(self) -> dict:
        """Liveness + load + the engine's SLO verdict."""
        if not self._alive:
            return {"alive": False, "state": "down", "load": 0}
        return {"alive": True, "load": self.load(),
                "free_pages": self.free_pages(),
                **self.engine.health()}

    # -- driver -------------------------------------------------------

    def _loop(self) -> None:
        eng = self.engine
        try:
            while True:
                with self._cv:
                    while (not self._mailbox and not self._stop_req
                           and not self._killed
                           and not eng.has_work()):
                        # bounded wait: has_work() can also change via
                        # the engine's own deadline clock
                        self._cv.wait(0.05)
                    if self._killed:
                        raise ReplicaDown(
                            f"replica {self.name}: killed")
                    if self._stop_req:
                        break
                    cmds = list(self._mailbox)
                    self._mailbox.clear()
                for cmd in cmds:
                    self._exec(cmd)
                if eng.has_work():
                    for res in eng.step():
                        self._deliver(res)
                with self._cv:
                    self._cv.notify_all()  # wake quiesce()
        except BaseException as e:  # driver death == replica death
            self._die(e)
            return
        self._shutdown()

    def _exec(self, cmd) -> None:
        if cmd[0] == "swap":
            _, variables, done = cmd
            try:
                self.engine.swap_variables(variables)
                done(None)
            except Exception as e:
                done(e)
            return
        if cmd[0] == "kv":
            _, (op, payload), done = cmd
            try:
                if op == "probe":
                    done(self.engine.match_blocks(payload))
                elif op == "export":
                    done(self.engine.export_prefix(payload))
                else:
                    done(self.engine.import_prefix(
                        payload["prompt"], payload["blocks"],
                        payload.get("weights_ver")))
            except Exception as e:
                done(e)
            return
        _, spec, cb = cmd
        kwargs = {}
        for k in ("max_new_tokens", "eos_id", "deadline", "meta",
                  "tenant", "priority", "speculative"):
            if k in spec:
                kwargs[k] = spec[k]
        try:
            rid = self.engine.submit(spec["prompt"],
                                     request_id=spec["request_id"],
                                     **kwargs)
        except Exception as e:  # ShedError, validation, closed engine
            cb(e)
            return
        with self._cv:
            self._pending[rid] = cb

    def _deliver(self, res: dict) -> None:
        with self._cv:
            cb = self._pending.pop(res["request_id"], None)
            if not self._pending and not self._mailbox:
                self._cv.notify_all()
        if cb is not None:
            cb(res)

    def _take_all(self) -> tuple[dict, list]:
        with self._cv:
            self._alive = False
            pending, self._pending = self._pending, {}
            cmds = list(self._mailbox)
            self._mailbox.clear()
            self._cv.notify_all()
        return pending, cmds

    def _fail_cmds(self, cmds, exc: Exception) -> None:
        # both command kinds carry their callback third; both accept
        # an exception as the terminal outcome
        for cmd in cmds:
            with contextlib.suppress(Exception):
                cmd[2](exc)

    def _die(self, exc: BaseException) -> None:
        pending, cmds = self._take_all()
        telemetry.metrics().counter("gateway_replica_down_total",
                                    replica=self.name).inc()
        flight_recorder.record("replica_down", replica=self.name,
                               error=repr(exc))
        flight_recorder.flush()
        with contextlib.suppress(Exception):
            self.engine.close()  # release pools; results irrelevant
        down = ReplicaDown(f"replica {self.name} died: {exc!r}")
        for cb in pending.values():
            with contextlib.suppress(Exception):
                cb(down)
        self._fail_cmds(cmds, down)

    def _shutdown(self) -> None:
        pending, cmds = self._take_all()
        try:
            results = {r["request_id"]: r
                       for r in self.engine.close()}
        except Exception:
            results = {}
        down = ReplicaDown(f"replica {self.name} stopped")
        for rid, cb in pending.items():
            with contextlib.suppress(Exception):
                cb(results.get(rid, down))
        self._fail_cmds(cmds, down)


# ---------------------------------------------------------------------
# socket arm
# ---------------------------------------------------------------------
#
# Protocol (every message framed by ``transport``, an optional 17-byte
# trace-context header first, then a command byte):
#   b"g" + pack_obj(spec)      -> pack_obj(result dict)   (generate)
#   b"h"                       -> pack_obj(health dict)
#   b"w" + pack_obj(variables) -> pack_obj({"ok"| "error"}) (swap)
#   b"v"                       -> pack_obj(variables)     (rollback src)
#   b"q"                       -> pack_obj({"ok"| "error"}) (quiesce)
#   b"s"                       -> connection closes        (stop server)
#   b"y" + pack_obj(prompt)    -> pack_obj({"blocks"|"error"}) (kv probe)
#   b"x" + pack_obj(prompt)    -> kv page-blocks frame     (kv export)
#   b"k" + kv page-blocks body -> pack_obj({"imported"|"error"})
# Payloads are flax msgpack (``pack_obj``) — self-describing, never
# pickle; a generate connection stays open for the whole request, so a
# severed wire maps 1:1 to a failed attempt.  The kv page-blocks frame
# is ``serving.pack_kv_blocks``'s gather-sent wire form (scope
# ``"kv"``): raw page memoryviews behind a length-prefixed msgpack
# meta, so exported KV never round-trips through msgpack arrays.


def _exc_error(e: Exception) -> str:
    if isinstance(e, ShedError):
        return f"shed: {e}"
    if isinstance(e, ReplicaDown):
        return f"replica_down: {e}"
    return f"replica_error: {e!r}"


class ReplicaServer:
    """Serve one ``EngineReplica`` over the socket transport.

    Mirrors ``PSServer``'s accept-loop shape (daemon handler thread
    per connection, 0.2s accept poll, trace-linked rpc spans), so the
    chaos and tracing machinery built for the PS wire applies
    unchanged to the serving wire.
    """

    def __init__(self, replica: EngineReplica,
                 host: str = "127.0.0.1", port: int = 0):
        self.replica = replica
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET,
                              socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address = self._sock.getsockname()
        self._conns: list[socket.socket] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"dkt-replica-srv-{replica.name}")

    def start(self) -> "ReplicaServer":
        self.replica.start()
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.2)
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                conn.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                self._conns.append(conn)
                threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True).start()
        finally:
            with contextlib.suppress(OSError):
                self._sock.close()

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            try:
                while True:
                    msg = transport.recv_msg(conn)
                    link, msg = transport.split_trace_header(msg)
                    cmd, body = bytes(msg[:1]), msg[1:]
                    with contextlib.ExitStack() as rpc:
                        if link is not None:
                            rpc.enter_context(telemetry.span(
                                "replica_rpc", cmd=cmd.decode(),
                                replica=self.replica.name,
                                link_trace=format(link[0], "x"),
                                link_span=format(link[1], "x")))
                            telemetry.flow_end("wire", link[1],
                                               cmd=cmd.decode())
                        self._dispatch(conn, cmd, body)
                    if self._stop.is_set():
                        return
            except (ConnectionError, OSError):
                return  # client gone / chaos-severed

    def _dispatch(self, conn: socket.socket, cmd: bytes,
                  body: bytes) -> None:
        rep = self.replica
        if cmd == b"g":
            spec = transport.unpack_obj(body)
            spec["prompt"] = np.asarray(spec["prompt"], np.int32)
            fut = _Future()
            try:
                rep.dispatch(spec, fut.set)
                res = fut.wait()
            except Exception as e:
                res = e
            if isinstance(res, Exception):
                res = {"request_id": spec.get("request_id"),
                       "prompt": spec["prompt"],
                       "tokens": np.zeros((0,), np.int32),
                       "error": _exc_error(res)}
            transport.send_msg(conn, transport.pack_obj(
                jax.device_get(res)))
        elif cmd == b"h":
            transport.send_msg(conn,
                               transport.pack_obj(rep.health()))
        elif cmd == b"w":
            try:
                rep.swap(transport.unpack_obj(body))
                out = {"ok": True}
            except Exception as e:
                out = {"error": _exc_error(e)}
            transport.send_msg(conn, transport.pack_obj(out))
        elif cmd == b"v":
            transport.send_msg(conn, transport.pack_obj(
                jax.device_get(rep.variables())))
        elif cmd == b"q":
            try:
                rep.quiesce()
                out = {"ok": True}
            except Exception as e:
                out = {"error": _exc_error(e)}
            transport.send_msg(conn, transport.pack_obj(out))
        elif cmd == b"y":
            prompt = np.asarray(transport.unpack_obj(body), np.int32)
            try:
                out = {"blocks": int(rep.kv_probe(prompt))}
            except Exception as e:
                out = {"error": _exc_error(e)}
            transport.send_msg(conn, transport.pack_obj(out))
        elif cmd == b"x":
            prompt = np.asarray(transport.unpack_obj(body), np.int32)
            try:
                export = rep.kv_export(prompt)
            except Exception:
                export = None  # export is best-effort: reply empty,
                #                the importer recomputes instead
            if export is None:
                export = {"prompt": prompt, "blocks": []}
            transport.send_msg_gather(conn, *pack_kv_blocks(export))
        elif cmd == b"k":
            try:
                out = {"imported": int(rep.kv_import(
                    unpack_kv_blocks(body)))}
            except Exception as e:
                out = {"error": _exc_error(e)}
            transport.send_msg(conn, transport.pack_obj(out))
        elif cmd == b"s":
            self.stop()
        else:
            raise ValueError(f"unknown command {cmd!r}")

    def stop(self) -> None:
        """Graceful: stop accepting; live requests finish; the replica
        (and its engine) shut down cleanly."""
        self._stop.set()
        with contextlib.suppress(OSError):
            self._sock.close()
        self.replica.stop()

    def kill(self) -> None:
        """Crash simulation: sever the listener, every live
        connection, AND the driver — clients see ``ConnectionError``
        mid-frame and the gateway fails their requests over.  The
        flight marker is fsynced first, as on ``PSServer.kill``."""
        flight_recorder.record("replica_down",
                               replica=self.replica.name,
                               error="killed", port=self.address[1])
        flight_recorder.flush(fsync=True)
        self._stop.set()
        for s in (self._sock, *self._conns):
            with contextlib.suppress(OSError):
                s.close()
        self.replica.kill()


class RemoteReplica:
    """Gateway-side proxy for a ``ReplicaServer``.

    Each generate attempt runs on its own dispatch thread over its own
    connection (``trace_header()`` + ``flow_start`` pair the client
    span with the server's ``replica_rpc`` span in a merged trace), so
    a severed wire fails exactly one attempt.  Any transport-level
    failure marks the proxy down — the gateway stops routing here
    until ``probe()`` succeeds again.
    """

    def __init__(self, host: str, port: int,
                 name: Optional[str] = None, *,
                 attempt_timeout: Optional[float] = None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = int(port)
        self.name = name if name is not None else f"{host}:{port}"
        self.attempt_timeout = attempt_timeout
        self.connect_timeout = connect_timeout
        self._lock = racecheck.lock("gateway.remote")
        self._alive = True  # guarded-by: _lock
        self._outstanding = 0  # guarded-by: _lock
        self._free_pages = None  # last health-reported page headroom

    def start(self) -> "RemoteReplica":
        return self  # the server owns the engine lifecycle

    @property
    def alive(self) -> bool:
        return self._alive

    def load(self) -> int:
        return self._outstanding

    def free_pages(self) -> Optional[int]:
        """Page headroom as of the last ``health()``/``probe()``
        round-trip (``None`` until one lands, or for envelope-pool
        servers) — a cached snapshot, not a live read: routing must
        not pay an RPC per choice."""
        return self._free_pages

    def _exchange(self, cmd: bytes, body: bytes = b"",
                  timeout: Optional[float] = None):
        # transport.* looked up at call time: the ChaosTransport choke
        # point must see this hop
        sock = transport.connect(self.host, self.port,
                                 timeout=self.connect_timeout)
        try:
            if timeout is not None:
                sock.settimeout(timeout)
            hdr = transport.trace_header()
            transport.send_msg(sock, hdr + cmd, body)
            if hdr:
                ctx = telemetry.current_trace()
                telemetry.flow_start("wire", ctx[1],
                                     cmd=cmd.decode())
            return transport.unpack_obj(transport.recv_msg(sock))
        finally:
            with contextlib.suppress(OSError):
                sock.close()

    def _mark_down(self, exc: Exception) -> None:
        with self._lock:
            was = self._alive
            self._alive = False
        if was:
            telemetry.metrics().counter("gateway_replica_down_total",
                                        replica=self.name).inc()
            flight_recorder.record("replica_down", replica=self.name,
                                   error=repr(exc))
            flight_recorder.flush()

    def probe(self) -> bool:
        """One health round-trip; revives a down-marked proxy when the
        server is reachable again (the warm-restart story)."""
        try:
            out = self._exchange(b"h", timeout=self.connect_timeout)
        except (ConnectionError, OSError, ValueError):
            return False
        with self._lock:  # revival races dispatch's _mark_down
            self._alive = True
            if isinstance(out, Mapping):
                self._free_pages = out.get("free_pages")
        return True

    def dispatch(self, spec: Mapping, on_result: Callable) -> None:
        if not self._alive:
            raise ReplicaDown(f"replica {self.name} is down")
        with self._lock:
            self._outstanding += 1
        threading.Thread(target=self._run_request,
                         args=(dict(spec), on_result),
                         daemon=True).start()

    def _run_request(self, spec: dict, on_result: Callable) -> None:
        try:
            with telemetry.span("gateway_rpc", replica=self.name,
                                request_id=str(spec["request_id"])):
                wire = dict(spec)
                wire["prompt"] = np.asarray(spec["prompt"], np.int32)
                out = self._exchange(
                    b"g", transport.pack_obj(wire),
                    timeout=self.attempt_timeout)
                if isinstance(out.get("tokens"), np.ndarray):
                    out["tokens"] = out["tokens"].astype(np.int32)
        except Exception as e:
            self._mark_down(e)
            out = e
        finally:
            with self._lock:
                self._outstanding -= 1
        on_result(out)

    def swap(self, variables: Mapping,
             timeout: float = 120.0) -> None:
        out = self._exchange(
            b"w", transport.pack_obj(jax.device_get(dict(variables))),
            timeout=timeout)
        if "error" in out:
            raise ValueError(f"remote swap failed: {out['error']}")

    def variables(self) -> Mapping:
        return self._exchange(b"v", timeout=120.0)

    def quiesce(self, timeout: float = 60.0) -> None:
        out = self._exchange(b"q", timeout=timeout)
        if "error" in out:
            raise TimeoutError(
                f"remote quiesce failed: {out['error']}")

    # -- disaggregated prefill/decode handoff -------------------------

    def kv_probe(self, prompt, timeout: float = 60.0) -> int:
        try:
            out = self._exchange(
                b"y",
                transport.pack_obj(np.asarray(prompt, np.int32)),
                timeout=timeout)
        except (ConnectionError, OSError) as e:
            self._mark_down(e)
            raise
        if "error" in out:
            raise ReplicaDown(f"kv_probe failed: {out['error']}")
        return int(out["blocks"])

    def kv_export(self, prompt, timeout: float = 60.0):
        """Pull a prompt's cached KV blocks off the remote replica —
        the reply is the raw kv page-blocks frame (``unpack_kv_blocks``
        decodes it in place on the receive buffer, no msgpack detour
        for the page bytes).  ``None`` when nothing is cached."""
        sock = transport.connect(self.host, self.port,
                                 timeout=self.connect_timeout)
        try:
            sock.settimeout(timeout)
            hdr = transport.trace_header()
            transport.send_msg(
                sock, hdr + b"x",
                transport.pack_obj(np.asarray(prompt, np.int32)))
            export = unpack_kv_blocks(transport.recv_msg_into(sock))
        except (ConnectionError, OSError) as e:
            self._mark_down(e)
            raise
        finally:
            with contextlib.suppress(OSError):
                sock.close()
        return export if export["n_blocks"] else None

    def kv_import(self, export: Mapping,
                  timeout: float = 60.0) -> int:
        """Ship a block set into the remote replica's prefix store —
        ONE gather-sent frame, the page memoryviews riding ``sendmsg``
        with zero send-side copies."""
        sock = transport.connect(self.host, self.port,
                                 timeout=self.connect_timeout)
        try:
            sock.settimeout(timeout)
            hdr = transport.trace_header()
            parts = pack_kv_blocks(export)
            transport.send_msg_gather(sock, hdr + b"k", *parts)
            out = transport.unpack_obj(transport.recv_msg(sock))
        except (ConnectionError, OSError) as e:
            self._mark_down(e)
            raise
        finally:
            with contextlib.suppress(OSError):
                sock.close()
        if "error" in out:
            raise ReplicaDown(f"kv_import failed: {out['error']}")
        return int(out["imported"])

    def health(self) -> dict:
        try:
            out = self._exchange(b"h",
                                 timeout=self.connect_timeout)
        except (ConnectionError, OSError, ValueError):
            return {"alive": False, "state": "down", "load": 0}
        if isinstance(out, Mapping):
            with self._lock:
                self._free_pages = out.get("free_pages")
        return out

    def stop_server(self) -> None:
        with contextlib.suppress(ConnectionError, OSError):
            sock = transport.connect(self.host, self.port,
                                     timeout=self.connect_timeout)
            try:
                transport.send_msg(sock, b"s")
            finally:
                with contextlib.suppress(OSError):
                    sock.close()


# ---------------------------------------------------------------------
# the gateway
# ---------------------------------------------------------------------


class _GwRequest:
    __slots__ = ("rid", "spec", "future", "attempts", "tried")

    def __init__(self, rid, spec):
        self.rid = rid
        self.spec = spec
        self.future = _Future()
        self.attempts = 0  # failed attempts so far
        self.tried: set = set()  # replica names already tried


def _classify(res) -> str:
    """``final`` (deliver as-is), ``failover`` (replica failed — count
    + reroute), or ``shed`` (backpressure — retry after backoff
    without calling it a failover)."""
    if isinstance(res, ShedError):
        return "shed"
    if isinstance(res, (ReplicaDown, ConnectionError, OSError,
                        TimeoutError)):
        return "failover"
    if isinstance(res, ValueError) and "in flight" in str(res):
        # the id is still live on that engine (a slow attempt we
        # failed over from) — route elsewhere, don't fail the request
        return "failover"
    if isinstance(res, Exception):
        return "final"
    err = res.get("error")
    if err is None:
        return "final"
    err = str(err)
    if err.startswith("shed"):
        return "shed"
    if err.startswith(("replica_down", "engine_closed")):
        return "failover"
    return "final"  # deadline_exceeded, prefill_failed, replica_error


def _cause(res) -> str:
    return repr(res) if isinstance(res, Exception) \
        else str(res.get("error"))


def _free_pages(rep) -> Optional[int]:
    """A replica's page headroom, ``None`` for envelope replicas (or
    anything not exposing the signal) — the shared routing probe."""
    fn = getattr(rep, "free_pages", None)
    return fn() if callable(fn) else None


class ServingGateway:
    """Route requests over replicas; fail over; roll weights.

    Args:
      replicas: ``EngineReplica`` / ``RemoteReplica`` instances (or
        anything duck-typing their surface).  Names must be unique.
      policy: ``round_robin`` | ``least_loaded`` | ``session`` (sticky
        by the ``session=`` key passed to ``submit``; requests without
        a session key fall back to round-robin) | ``prefix`` (sticky
        by the first ``prefix_block`` prompt tokens, so requests that
        share a system prompt land on the replica whose prefix cache
        is warm — the RadixAttention affinity idea at gateway level;
        composes with failover: a dead replica's key range just hashes
        over the survivors).
      prefix_block: prompt-head length (tokens) hashed by the
        ``prefix`` policy; align it with the engines'
        ``prefill_align`` so requests that share a cacheable prefix
        share a replica.
      retries: failed attempts per request beyond the first before the
        request is completed as ``error="gateway_retries_exhausted"``.
      backoff_base/backoff_max/jitter/seed: full-jitter exponential
        backoff between attempts — the ``ResilientPSClient``
        discipline (``delay = min(max, base * 2**(n-1)) * (1 -
        jitter*u)``), seeded so a chaos sweep's retry timing is
        reproducible.
      deadline: default per-attempt decode budget handed to the
        engine (seconds from engine admission; gateway queue/backoff
        time is NOT counted — each attempt gets a fresh budget).

    Delivery semantics: ``submit`` returns a request id;
    ``result(rid)`` blocks for its single result.  Success results are
    the engine's dicts verbatim; terminal failures come back as
    ``error`` result dicts (never exceptions), matching the engine's
    own error-row contract.  A request is delivered exactly once even
    if two attempts both complete (first wins).
    """

    def __init__(self, replicas: Iterable, *,
                 policy: str = "round_robin", retries: int = 3,
                 backoff_base: float = 0.02, backoff_max: float = 0.5,
                 jitter: float = 0.5, seed: int = 0,
                 deadline: Optional[float] = None,
                 prefix_block: int = 128):
        self._replicas = list(replicas)
        if not self._replicas:
            raise ValueError("ServingGateway needs >= 1 replica")
        names = [r.name for r in self._replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; choose from {POLICIES}")
        if retries < 0:
            raise ValueError(f"retries must be >= 0; got {retries}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter={jitter} outside [0, 1]")
        if prefix_block < 1:
            raise ValueError(
                f"prefix_block must be >= 1; got {prefix_block}")
        self.policy = policy
        self.prefix_block = int(prefix_block)
        self.retries = int(retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.deadline = deadline
        self._rng = np.random.default_rng(seed)
        self._lock = racecheck.rlock("gateway")
        self._requests: dict[Any, _GwRequest] = {}
        self._rr = 0  # guarded-by: _lock
        self._n_auto = itertools.count()
        self._seq = itertools.count()  # retry-queue tiebreaker
        self._updating: set = set()  # replica names mid-swap
        self._closing = False  # guarded-by: _lock
        self._started = False  # guarded-by: _lock
        self._retry_q: queue.PriorityQueue = queue.PriorityQueue()
        self._retry_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ServingGateway":
        with self._lock:
            if self._started:
                return self
            self._started = True
        for rep in self._replicas:
            rep.start()
        self._retry_thread = threading.Thread(
            target=self._retry_loop, daemon=True,
            name="dkt-gateway-retry")
        self._retry_thread.start()
        return self

    def stop(self) -> None:
        """Shut down: local replicas close their engines (in-flight
        requests complete as ``engine_closed`` error results, without
        failover); remote replica SERVERS are left running — they are
        owned by whoever started them."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
        self._retry_q.put((0.0, -1, None))  # wake + exit
        for rep in self._replicas:
            if isinstance(rep, EngineReplica):
                rep.stop()
        if self._retry_thread is not None:
            self._retry_thread.join(5.0)
        # anything still unresolved (e.g. queued behind a dead retry)
        # is failed out rather than leaking a waiter forever
        with self._lock:
            reqs = list(self._requests.values())
        for req in reqs:
            if not req.future.ready():
                self._complete(req, self._error_result(
                    req, "gateway_closed"))

    def __enter__(self) -> "ServingGateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission ---------------------------------------------------

    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               eos_id=_UNSET, request_id=None, deadline=_UNSET,
               session=None, meta: Optional[Mapping] = None,
               tenant=None, priority: Optional[int] = None,
               speculative=None, handoff: bool = False):
        """Queue one request; returns its id.  ``session`` is the
        affinity key for the ``session`` policy; ``tenant``/
        ``priority`` ride through to the engine's QoS scheduler
        (inert on envelope-pool replicas); ``speculative`` is the
        per-request speculation override, forwarded only when set
        (replicas without an engine-level ``speculative=`` config
        reject it); ``handoff`` marks a disaggregated decode-side
        dispatch whose KV pages already shipped in, exempting it
        from the page-exhaustion routing exclusion (it never reaches
        the engine).  Explicit ``request_id``s
        must be unique among unresolved gateway requests (and
        msgpack-encodable for remote replicas)."""
        self.start()
        spec: dict = {"prompt": np.asarray(prompt, np.int32)}
        if handoff:
            spec["handoff"] = True
        if max_new_tokens is not None:
            spec["max_new_tokens"] = int(max_new_tokens)
        if eos_id is not _UNSET:
            spec["eos_id"] = eos_id
        dl = self.deadline if deadline is _UNSET else deadline
        if dl is not None:
            spec["deadline"] = float(dl)
        if meta:
            spec["meta"] = dict(meta)
        if session is not None:
            spec["session"] = session
        if tenant is not None:
            spec["tenant"] = tenant
        if priority is not None:
            spec["priority"] = int(priority)
        if speculative is not None:
            spec["speculative"] = bool(speculative)
        with self._lock:
            if self._closing:
                raise RuntimeError("gateway is closed")
            if request_id is None:
                rid = f"gw-{next(self._n_auto)}"
                while rid in self._requests:
                    rid = f"gw-{next(self._n_auto)}"
            else:
                rid = request_id
                if rid in self._requests:
                    raise ValueError(
                        f"request_id {rid!r} is already in flight")
            spec["request_id"] = rid
            req = _GwRequest(rid, spec)
            self._requests[rid] = req
            telemetry.metrics().gauge("gateway_inflight_requests").set(
                len(self._requests))
        self._dispatch(req)
        return rid

    def result(self, request_id, timeout: Optional[float] = None
               ) -> dict:
        """Block for (and consume) one request's result."""
        with self._lock:
            req = self._requests.get(request_id)
        if req is None:
            raise KeyError(f"unknown request_id {request_id!r}")
        res = req.future.wait(timeout)
        with self._lock:
            self._requests.pop(request_id, None)
            telemetry.metrics().gauge("gateway_inflight_requests").set(
                len(self._requests))
        return res

    def try_result(self, request_id):
        """Non-blocking ``result``: the result dict when ready (and
        consumed), else ``None`` with the request left in flight.  The
        traffic simulator's pacing loop polls this between arrivals —
        it must never block behind one slow request while the offered
        load keeps its own clock."""
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                raise KeyError(f"unknown request_id {request_id!r}")
            if not req.future.ready():
                return None
            self._requests.pop(request_id, None)
            telemetry.metrics().gauge("gateway_inflight_requests").set(
                len(self._requests))
        return req.future.wait(0)

    def run(self, requests: Iterable, *, ordered: bool = True
            ) -> Iterator[dict]:
        """Serve an iterable to completion — the gateway-level
        ``DecodeEngine.run``.  Items are prompts or mappings with
        ``"prompt"`` (+ ``max_new_tokens``/``eos_id``/``session``/
        ``deadline``/``tenant``/``priority``/``speculative``; other
        keys ride into results as meta).  Engine
        sheds are absorbed by the failover/backoff machinery, so the
        whole iterable is always accounted for: one result per item.
        """
        rids = [self._submit_item(item) for item in requests]
        if ordered:
            for rid in rids:
                yield self.result(rid)
            return
        pending = set(rids)
        while pending:
            done = [rid for rid in pending
                    if self._requests[rid].future.ready()]
            for rid in done:
                pending.discard(rid)
                yield self.result(rid)
            if not done:
                _sleep(0.002)

    def _submit_item(self, item):
        if isinstance(item, Mapping):
            meta = {k: v for k, v in item.items()
                    if k not in ("prompt", "max_new_tokens", "eos_id",
                                 "session", "deadline", "tenant",
                                 "priority", "speculative")}
            return self.submit(
                item["prompt"],
                max_new_tokens=item.get("max_new_tokens"),
                eos_id=item.get("eos_id", _UNSET),
                deadline=item.get("deadline", _UNSET),
                session=item.get("session"),
                tenant=item.get("tenant"),
                priority=item.get("priority"),
                speculative=item.get("speculative"), meta=meta)
        return self.submit(item)

    # -- routing ------------------------------------------------------

    def _choosable(self) -> list:
        return [r for r in self._replicas
                if r.alive and r.name not in self._updating]

    def _choose(self, req: _GwRequest):
        with self._lock:
            cands = self._choosable()
            if not cands:
                return None
            fresh = [r for r in cands if r.name not in req.tried]
            cands = fresh or cands  # all tried: go around again
            if not req.spec.get("handoff"):
                # a paged replica with ZERO free pages cannot admit a
                # fresh prefill without parking or shedding it — skip
                # page-exhausted replicas for NEW admissions under
                # every policy.  Handoff dispatches are exempt: the
                # disaggregated router already page-checked its decode
                # target, and excluding it here would unstick the
                # request from the replica its KV just shipped to.
                # All-exhausted falls through unchanged (the engine's
                # own parking/shedding beats a gateway-level drop).
                roomy = [r for r in cands
                         if _free_pages(r) != 0]
                cands = roomy or cands
            if self.policy == "least_loaded":
                # ties on load break on paged headroom (more free KV
                # pages first, so paged replicas absorb the burst);
                # envelope replicas report None and sort as 0 —
                # between queue depth and an exhausted paged pool
                def _key(r):
                    fp = _free_pages(r)
                    return (r.load(), 0 if fp is None else -fp,
                            r.name)
                return min(cands, key=_key)
            if (self.policy == "session"
                    and req.spec.get("session") is not None):
                cands = sorted(cands, key=lambda r: r.name)
                key = str(req.spec["session"]).encode()
                return cands[zlib.crc32(key) % len(cands)]
            if self.policy == "prefix":
                # deterministic over the SORTED candidate set, same
                # as session stickiness: equal prompt heads map to
                # the same replica as long as the replica set is
                # stable, and rehash consistently when it shrinks
                cands = sorted(cands, key=lambda r: r.name)
                key = req.spec["prompt"][:self.prefix_block].tobytes()
                return cands[zlib.crc32(key) % len(cands)]
            rep = cands[self._rr % len(cands)]
            self._rr += 1
            return rep

    def _dispatch(self, req: _GwRequest) -> None:
        rep = self._choose(req)
        if rep is None:
            # nothing routable: down-marked remotes may only have had
            # a transient wire fault — probe before burning an attempt
            for r in self._replicas:
                probe = getattr(r, "probe", None)
                if probe is not None and not r.alive:
                    with contextlib.suppress(Exception):
                        probe()
            rep = self._choose(req)
        if rep is None:
            # nothing routable NOW (all down or mid-update): burn one
            # attempt waiting rather than failing a survivable blip
            self._retry(req, None, "no_replica_available",
                        kind="failover")
            return
        req.tried.add(rep.name)
        telemetry.metrics().counter("gateway_requests_total",
                                    replica=rep.name,
                                    policy=self.policy).inc()
        try:
            rep.dispatch(req.spec,
                         lambda res: self._on_result(req, rep, res))
        except Exception as e:  # refused at the door (down/racing)
            self._on_result(req, rep, e)

    def _on_result(self, req: _GwRequest, rep, res) -> None:
        if req.future.ready():
            return  # a faster attempt already won
        kind = _classify(res)
        if self._closing or kind == "final":
            self._complete(req, res)
            return
        name = rep.name if rep is not None else "(none)"
        if kind == "failover":
            telemetry.metrics().counter("gateway_failovers_total",
                                        replica=name).inc()
            flight_recorder.record("failover", request_id=req.rid,
                                   replica=name, cause=_cause(res),
                                   attempt=req.attempts + 1)
        else:
            telemetry.metrics().counter("gateway_shed_retries_total",
                                        replica=name).inc()
        self._retry(req, rep, _cause(res), kind=kind)

    def _retry(self, req: _GwRequest, rep, cause: str, *,
               kind: str) -> None:
        req.attempts += 1
        if req.attempts > self.retries:
            telemetry.metrics().counter(
                "gateway_retries_exhausted_total").inc()
            self._complete(req, self._error_result(
                req, f"gateway_retries_exhausted: {cause}"))
            return
        self._retry_q.put((telemetry.now()
                           + self._backoff_delay(req.attempts),
                           next(self._seq), req))

    def _backoff_delay(self, attempt: int) -> float:
        delay = min(self.backoff_max,
                    self.backoff_base * 2 ** (attempt - 1))
        with self._lock:
            u = float(self._rng.random())
        return delay * (1.0 - self.jitter * u)

    def _retry_loop(self) -> None:
        while True:
            due, _, req = self._retry_q.get()
            if req is None:
                return
            wait = due - telemetry.now()
            if wait > 0:
                _sleep(wait)
            if self._closing:
                if not req.future.ready():
                    self._complete(req, self._error_result(
                        req, "gateway_closed"))
                continue
            self._dispatch(req)

    def _complete(self, req: _GwRequest, res) -> None:
        if isinstance(res, Exception):
            res = self._error_result(req, f"gateway: {res!r}")
        req.future.set(res)

    def _error_result(self, req: _GwRequest, error: str) -> dict:
        spec = req.spec
        return {**spec.get("meta", {}),
                "request_id": req.rid, "prompt": spec["prompt"],
                "tokens": np.zeros((0,), np.int32), "error": error,
                "attempts": req.attempts}

    # -- health -------------------------------------------------------

    def busy(self) -> bool:
        """True while any replica swap (``rolling_update`` /
        ``add_replica`` warm / ``remove_replica`` drain) is mid-flight
        — the ``Autoscaler(busy=gw.busy)`` guard, so scaling verbs
        never interleave with a live swap."""
        with self._lock:
            return bool(self._updating)

    def alive_replicas(self) -> int:
        """Routable capacity right now: replicas that are alive (a
        mid-update replica still counts — it comes back).  This is the
        ``Autoscaler(replica_count=...)`` hook and the drill's
        convergence observable."""
        return sum(1 for r in self._replicas if r.alive)

    def healthz(self) -> dict:
        """Aggregated verdict + per-replica verdicts.  ``critical``
        when no replica is alive; otherwise the worst alive replica's
        SLO state, floored at ``degraded`` while any replica is down
        or mid-update (capacity is reduced even if the survivors are
        healthy)."""
        rank = {"ok": 0, "degraded": 1, "critical": 2}
        replicas = {}
        worst, n_alive = "ok", 0
        with self._lock:
            updating = set(self._updating)
        for rep in self._replicas:
            h = rep.health()
            replicas[rep.name] = h
            if h.get("alive"):
                n_alive += 1
                s = h.get("state", "ok")
                if rank.get(s, 0) > rank[worst]:
                    worst = s
        if n_alive == 0:
            state = "critical"
        elif n_alive < len(self._replicas) or updating:
            state = worst if rank[worst] >= 1 else "degraded"
        else:
            state = worst
        telemetry.metrics().gauge("gateway_alive_replicas").set(
            n_alive)
        return {"state": state, "alive": n_alive,
                "total": len(self._replicas),
                "updating": sorted(updating), "replicas": replicas}

    # -- elastic membership -------------------------------------------

    def add_replica(self, replica, *, source=None,
                    quiesce_timeout: float = 60.0):
        """Admit a new replica without disturbing traffic: *register
        excluded* (routing never sees it yet, ``healthz`` shows it as
        updating) → *start* → *warm* (weights from ``source``, any
        form ``rolling_update`` accepts; default: a live peer, so the
        fleet stays uniform) → *admit*.  On any warm-up failure the
        replica is deregistered and the error re-raised — the serving
        set is never left with a cold member.  Returns the replica.
        """
        with self._lock:
            if self._closing:
                raise RuntimeError("gateway is closed")
            names = {r.name for r in self._replicas}
            if replica.name in names:
                raise ValueError(
                    f"replica name {replica.name!r} already "
                    f"registered")
            started = self._started
            self._updating.add(replica.name)
            self._replicas.append(replica)
        try:
            if started:
                replica.start()
            if source is None:
                with self._lock:
                    live = [r for r in self._replicas
                            if r.alive and r.name != replica.name]
                if live:
                    # a replica's variables() IS the full variables
                    # dict — _resolve_source passes it through
                    source = jax.device_get(dict(live[0].variables()))
            if source is not None and replica.alive:
                replica.swap(self._resolve_source(source))
        except Exception:
            with self._lock:
                self._replicas.remove(replica)
                self._updating.discard(replica.name)
            raise
        with self._lock:
            self._updating.discard(replica.name)
            total = len(self._replicas)
        flight_recorder.record("replica_add", replica=replica.name,
                               total=total)
        return replica

    def remove_replica(self, name: str, *,
                       quiesce_timeout: float = 60.0):
        """Drain a replica out of the serving set: *exclude from
        routing* → *quiesce* (its in-flight work completes; new
        requests already route elsewhere) → *deregister* → *stop* (a
        local ``EngineReplica``'s engine closes; a remote replica's
        server is left to its owner, same as ``stop()``).  Refuses to
        drain the last routable replica.  Returns the removed replica.
        """
        with self._lock:
            by_name = {r.name: r for r in self._replicas}
            rep = by_name.get(name)
            if rep is None:
                raise ValueError(f"no replica named {name!r}: "
                                 f"{sorted(by_name)}")
            routable = [r for r in self._replicas
                        if r.alive and r.name not in self._updating]
            if [r.name for r in routable] == [name]:
                raise ValueError(
                    f"refusing to drain {name!r}: it is the last "
                    f"routable replica")
            self._updating.add(name)
        try:
            if rep.alive:
                rep.quiesce(quiesce_timeout)
        finally:
            with self._lock:
                self._replicas.remove(rep)
                self._updating.discard(name)
                total = len(self._replicas)
        if isinstance(rep, EngineReplica):
            rep.stop()
        flight_recorder.record("replica_drain", replica=name,
                               total=total)
        return rep

    # -- rolling weight updates ---------------------------------------

    def _resolve_source(self, source) -> dict:
        """New weights from: a PS snapshot path, a live PS (``.center``
        — ``HostParameterServer`` / ``ShardedParameterServer``), a PS
        client (``.pull()``), a REPLICATED PS's address list (``[(host,
        port), ...]`` — each tried in order over the template-free
        ``b"V"`` center fetch, so the rollout sources from whichever
        replica currently serves; a fenced ex-primary refuses and the
        walk moves on), a ``{"params": ...}`` variables dict, or a raw
        parameter pytree."""
        import os

        if isinstance(source, (str, os.PathLike)):
            from distkeras_tpu import checkpoint

            params = checkpoint.ps_snapshot_center(source)
        elif (isinstance(source, (list, tuple)) and source
              and all(isinstance(a, (list, tuple)) and len(a) == 2
                      for a in source)):
            from distkeras_tpu.parallel import host_ps

            last_err: Exception | None = None
            for addr_host, addr_port in source:
                try:
                    obj = host_ps.fetch_center_obj(
                        str(addr_host), int(addr_port))
                    params = obj["center"]
                    break
                except (OSError, ValueError, KeyError) as e:
                    last_err = e
            else:
                raise ConnectionError(
                    f"no PS replica in {source!r} would serve the "
                    f"center") from last_err
        elif hasattr(source, "center"):
            params = source.center
        elif hasattr(source, "pull") and callable(source.pull):
            params = source.pull()
        elif isinstance(source, Mapping) and "params" in source:
            return dict(source)
        else:
            params = source
        return {"params": params}

    def rolling_update(self, source, *,
                       quiesce_timeout: float = 60.0,
                       health_check: Optional[Callable] = None
                       ) -> dict:
        """Drain + hot-swap one replica at a time while the rest keep
        serving; zero requests fail (draining excludes the replica
        from routing first, and the engine swap is rejected — not
        applied — on any structure mismatch).

        State machine per replica: *exclude from routing* → *quiesce*
        (drain its in-flight work) → *swap* (step-boundary install,
        no recompile) → *readmit* → *health re-check*.  If the check
        (default: the replica's own SLO verdict; pass
        ``health_check=lambda rep: ...`` to override) comes back
        ``critical``, every already-updated replica is rolled back to
        the pre-rollout weights and the rollout stops.  Dead replicas
        are skipped (they pick up current weights on restart).

        Returns ``{"updated": [...], "skipped": [...],
        "rolled_back": bool}``.
        """
        self.start()
        new_vars = self._resolve_source(source)
        check = health_check or (lambda rep: rep.health())
        report: dict = {"updated": [], "skipped": [],
                        "rolled_back": False}
        live = [r for r in self._replicas if r.alive]
        if not live:
            raise ReplicaDown("rolling_update: no replica alive")
        # the rollback image: the fleet is uniform between rollouts,
        # so any live replica's weights are THE previous version
        old_vars = jax.device_get(dict(live[0].variables()))
        with telemetry.span("rolling_update",
                            replicas=len(self._replicas)):
            for rep in self._replicas:
                if not rep.alive:
                    report["skipped"].append(rep.name)
                    continue
                self._swap_one(rep, new_vars, quiesce_timeout)
                verdict = check(rep)
                if verdict.get("state") == "critical":
                    self._rollback(report["updated"] + [rep.name],
                                   old_vars, quiesce_timeout)
                    report["rolled_back"] = True
                    report["verdict"] = verdict
                    return report
                report["updated"].append(rep.name)
        return report

    def _swap_one(self, rep, variables: Mapping,
                  quiesce_timeout: float) -> None:
        with telemetry.span("weight_swap", replica=rep.name):
            with self._lock:
                self._updating.add(rep.name)
            try:
                rep.quiesce(quiesce_timeout)
                rep.swap(variables)
            finally:
                with self._lock:
                    self._updating.discard(rep.name)
        telemetry.metrics().counter("gateway_weight_swaps_total",
                                    replica=rep.name).inc()
        flight_recorder.record("weight_swap", replica=rep.name)

    def _rollback(self, names: list, old_vars: Mapping,
                  quiesce_timeout: float) -> None:
        telemetry.metrics().counter("gateway_rollbacks_total").inc()
        flight_recorder.record("rollback", replicas=list(names))
        flight_recorder.flush()
        by_name = {r.name: r for r in self._replicas}
        with telemetry.span("rollback", replicas=len(names)):
            for name in names:
                rep = by_name[name]
                if rep.alive:
                    self._swap_one(rep, old_vars, quiesce_timeout)


# ---------------------------------------------------------------------
# disaggregated prefill/decode
# ---------------------------------------------------------------------


class PrefillDecodeRouter:
    """Two-stage disaggregated serving (the DistServe / Splitwise
    split): a PREFILL pool computes prompt KV, a DECODE pool owns
    token generation, and finished KV page blocks ship between them
    over the prefix-store interchange (``DecodeEngine.export_prefix``
    → wire scope ``"kv"`` → ``import_prefix``).

    Why: on a monolithic replica a long-prompt flood interleaves
    prefill programs with every live slot's decode steps, so INTER-
    TOKEN latency degrades fleet-wide.  Here the flood queues at the
    prefill pool — ``max_inflight_handoffs`` bounds prefill+export
    work in flight, the back-pressure valve — while decode replicas
    keep their step cadence (``scripts/perf_prefill_decode.py`` gates
    decode-side p99 flood-flatness on exactly this).

    Request lifecycle:

    * a SHORT prompt (under one whole ``block_size`` block — nothing
      exportable) routes straight to the decode pool;
    * a LONG prompt runs the pipeline: the least-loaded prefill
      replica generates ONE token (its donation path warms the
      prefill-side prefix store), ``kv_export`` pulls the prompt's
      blocks, then the router picks a decode replica with page
      headroom (``free_pages() >= `` the request's worst-case page
      need; envelope replicas always qualify), probes the target's
      LOCAL store first (``kv_probe`` — the cluster-tier rung: ship
      only when the decode side doesn't already hold the blocks),
      ``kv_import``s the set (``serving_kv_pages_shipped_total``
      counts shipped blocks), and dispatches the real request with
      ``handoff=True``.  Decode-side admission takes the ordinary
      prefix-hit path, so tokens are byte-identical to a monolithic
      engine by construction.
    * a dead prefill pool degrades gracefully: the request falls
      through to the decode pool and recomputes its prefill there.

    Failure discipline mirrors ``ServingGateway``: seeded full-jitter
    backoff, ``retries`` extra attempts per stage, first-completion-
    wins futures (exactly-once delivery), and a decode replica dying
    mid-handoff requeues the request onto a survivor — counted by
    ``serving_handoff_requeue_total`` plus a ``handoff_requeue``
    flight event (the seeded chaos test pins exactly-once delivery
    under the kill).
    """

    def __init__(self, prefill: Iterable, decode: Iterable, *,
                 block_size: int, max_inflight_handoffs: int = 4,
                 retries: int = 3, backoff_base: float = 0.02,
                 backoff_max: float = 0.5, jitter: float = 0.5,
                 seed: int = 0, deadline: Optional[float] = None):
        self.prefill = list(prefill)
        self.decode = list(decode)
        if not self.prefill or not self.decode:
            raise ValueError(
                "PrefillDecodeRouter needs >= 1 replica per pool")
        names = [r.name for r in (*self.prefill, *self.decode)]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1; got {block_size}")
        if max_inflight_handoffs < 1:
            raise ValueError(f"max_inflight_handoffs must be >= 1; "
                             f"got {max_inflight_handoffs}")
        if retries < 0:
            raise ValueError(f"retries must be >= 0; got {retries}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter={jitter} outside [0, 1]")
        # align block_size with the engines' page_size/prefill_align:
        # it sizes both the short-prompt cutoff and the page-headroom
        # requirement
        self.block_size = int(block_size)
        self.retries = int(retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.deadline = deadline
        self._rng = np.random.default_rng(seed)
        self._lock = racecheck.lock("gateway.pd_router")
        self._requests: dict[Any, tuple] = {}  # rid -> (spec, future)
        self._n_auto = itertools.count()
        self._handoffs = threading.Semaphore(
            int(max_inflight_handoffs))
        self._closing = False  # guarded-by: _lock
        self._started = False  # guarded-by: _lock

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "PrefillDecodeRouter":
        with self._lock:
            if self._started:
                return self
            self._started = True
        for rep in (*self.prefill, *self.decode):
            rep.start()
        # pre-touch: the handoff counters must exist (at zero) in
        # every snapshot obs_report reads, handoffs or none
        m = telemetry.metrics()
        m.counter("serving_kv_pages_shipped_total").inc(0)
        m.counter("serving_handoff_requeue_total").inc(0)
        return self

    def stop(self) -> None:
        with self._lock:
            if self._closing:
                return
            self._closing = True
        for rep in (*self.prefill, *self.decode):
            if isinstance(rep, EngineReplica):
                rep.stop()
        with self._lock:
            reqs = list(self._requests.items())
        for rid, (spec, fut) in reqs:
            if not fut.ready():
                fut.set(self._error_result(rid, spec,
                                           "gateway_closed"))

    def __enter__(self) -> "PrefillDecodeRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission ---------------------------------------------------

    def submit(self, prompt, *,
               max_new_tokens: Optional[int] = None, eos_id=_UNSET,
               request_id=None, deadline=_UNSET,
               meta: Optional[Mapping] = None, tenant=None,
               priority: Optional[int] = None):
        """Queue one request through the two-stage pipeline; returns
        its id.  Same result contract as ``ServingGateway.submit``."""
        self.start()
        spec: dict = {"prompt": np.asarray(prompt, np.int32)}
        if max_new_tokens is not None:
            spec["max_new_tokens"] = int(max_new_tokens)
        if eos_id is not _UNSET:
            spec["eos_id"] = eos_id
        dl = self.deadline if deadline is _UNSET else deadline
        if dl is not None:
            spec["deadline"] = float(dl)
        if meta:
            spec["meta"] = dict(meta)
        if tenant is not None:
            spec["tenant"] = tenant
        if priority is not None:
            spec["priority"] = int(priority)
        with self._lock:
            if self._closing:
                raise RuntimeError("router is closed")
            if request_id is None:
                rid = f"pd-{next(self._n_auto)}"
                while rid in self._requests:
                    rid = f"pd-{next(self._n_auto)}"
            else:
                rid = request_id
                if rid in self._requests:
                    raise ValueError(
                        f"request_id {rid!r} is already in flight")
            spec["request_id"] = rid
            fut = _Future()
            self._requests[rid] = (spec, fut)
        threading.Thread(target=self._run_one, args=(rid, spec, fut),
                         daemon=True,
                         name=f"dkt-pd-{rid}").start()
        return rid

    def result(self, request_id,
               timeout: Optional[float] = None) -> dict:
        """Block for (and consume) one request's result."""
        with self._lock:
            ent = self._requests.get(request_id)
        if ent is None:
            raise KeyError(f"unknown request_id {request_id!r}")
        res = ent[1].wait(timeout)
        with self._lock:
            self._requests.pop(request_id, None)
        return res

    def try_result(self, request_id):
        """Non-blocking ``result`` (``None``: still in flight)."""
        with self._lock:
            ent = self._requests.get(request_id)
            if ent is None:
                raise KeyError(f"unknown request_id {request_id!r}")
            if not ent[1].ready():
                return None
            self._requests.pop(request_id, None)
        return ent[1].wait(0)

    def run(self, requests: Iterable, *, ordered: bool = True
            ) -> Iterator[dict]:
        """Serve an iterable to completion — one result per item,
        same item forms as ``ServingGateway.run`` (minus ``session``/
        ``speculative``, which have no disaggregated meaning yet)."""
        rids = [self._submit_item(item) for item in requests]
        if ordered:
            for rid in rids:
                yield self.result(rid)
            return
        pending = set(rids)
        while pending:
            done = [rid for rid in pending
                    if self._requests[rid][1].ready()]
            for rid in done:
                pending.discard(rid)
                yield self.result(rid)
            if not done:
                _sleep(0.002)

    def _submit_item(self, item):
        if isinstance(item, Mapping):
            meta = {k: v for k, v in item.items()
                    if k not in ("prompt", "max_new_tokens", "eos_id",
                                 "deadline", "tenant", "priority")}
            return self.submit(
                item["prompt"],
                max_new_tokens=item.get("max_new_tokens"),
                eos_id=item.get("eos_id", _UNSET),
                deadline=item.get("deadline", _UNSET),
                tenant=item.get("tenant"),
                priority=item.get("priority"), meta=meta)
        return self.submit(item)

    # -- the pipeline -------------------------------------------------

    def _run_one(self, rid, spec: dict, fut: _Future) -> None:
        try:
            prompt = spec["prompt"]
            export = None
            if len(prompt) // self.block_size > 0:
                with self._handoffs:  # back-pressure: floods wait HERE
                    export = self._prefill_stage(rid, spec, fut)
                if fut.ready():
                    return
            self._decode_stage(rid, spec, fut, export)
        except Exception as e:  # never leak a waiter
            fut.set(self._error_result(rid, spec, f"router: {e!r}"))

    def _prefill_stage(self, rid, spec: dict, fut: _Future):
        """Prefill the prompt on the prefill pool and pull its KV
        blocks.  Best-effort by design: every failure path returns
        ``None`` and the decode stage recomputes — degraded latency,
        never a lost request."""
        prompt = spec["prompt"]
        rep = None
        for attempt in range(self.retries + 1):
            if self._closing or fut.ready():
                return None
            rep = self._pick(self.prefill)
            if rep is None:
                self._backoff(attempt + 1)
                continue
            pspec = {"prompt": prompt, "max_new_tokens": 1,
                     "request_id": f"{rid}#p{attempt}"}
            if "deadline" in spec:
                pspec["deadline"] = spec["deadline"]
            att = _Future()
            telemetry.metrics().counter(
                "gateway_requests_total", replica=rep.name,
                policy="prefill_decode").inc()
            try:
                with telemetry.span("prefill_stage", replica=rep.name,
                                    request_id=str(rid)):
                    rep.dispatch(pspec, att.set)
                    res = att.wait()
            except Exception as e:
                res = e
            if (_classify(res) == "final"
                    and not isinstance(res, Exception)
                    and res.get("error") is None):
                break
            self._backoff(attempt + 1)
        else:
            return None  # pool down/erroring: recompute on decode
        try:
            return rep.kv_export(prompt)
        except Exception:
            return None  # severed mid-export: recompute on decode

    def _decode_stage(self, rid, spec: dict, fut: _Future,
                      export) -> None:
        m = telemetry.metrics()
        need = paging.pages_for(
            len(spec["prompt"]) + int(spec.get("max_new_tokens", 1)),
            self.block_size)
        dspec = dict(spec)
        dspec["handoff"] = True
        last = None
        for attempt in range(self.retries + 1):
            if self._closing or fut.ready():
                return
            rep = self._pick(self.decode, need_pages=need)
            if rep is None:
                last = ReplicaDown("no decode replica available")
                self._backoff(attempt + 1)
                continue
            if export is not None and export["n_blocks"]:
                try:
                    # cluster-tier rung: the decode replica's LOCAL
                    # store first; ship only when it is missing blocks
                    if (rep.kv_probe(export["prompt"])
                            < export["n_blocks"]):
                        shipped = rep.kv_import(export)
                        m.counter(
                            "serving_kv_pages_shipped_total").inc(
                                shipped)
                except Exception as e:  # died mid-handoff: requeue
                    last = e
                    self._requeue(rid, rep, e, attempt)
                    continue
            att = _Future()
            m.counter("gateway_requests_total", replica=rep.name,
                      policy="prefill_decode").inc()
            try:
                rep.dispatch(dspec, att.set)
                res = att.wait()
            except Exception as e:
                res = e
            if _classify(res) == "final":
                self._complete(rid, spec, fut, res)
                return
            last = res
            self._requeue(rid, rep, res, attempt)
        self._complete(rid, spec, fut, self._error_result(
            rid, spec, f"handoff_retries_exhausted: {_cause(last)}"))

    def _pick(self, pool: list, need_pages: Optional[int] = None):
        """Least-loaded alive replica (ties: more free pages, then
        name).  With ``need_pages``, paged replicas short of that
        headroom are skipped — envelope replicas (``free_pages() is
        None``) always qualify — falling back to the full candidate
        set when every paged replica is short (the engine's own
        parking/shedding then applies back-pressure)."""
        cands = [r for r in pool if r.alive]
        if not cands:
            # down-marked remotes may only have had a transient wire
            # fault (chaos reset, server restart) — probe before
            # writing the whole pool off, as ServingGateway does
            for r in pool:
                probe = getattr(r, "probe", None)
                if probe is not None and not r.alive:
                    with contextlib.suppress(Exception):
                        probe()
            cands = [r for r in pool if r.alive]
        if not cands:
            return None
        if need_pages is not None:
            roomy = [r for r in cands
                     if (_free_pages(r) is None
                         or _free_pages(r) >= need_pages)]
            cands = roomy or cands
        def _key(r):
            fp = _free_pages(r)
            return (r.load(), 0 if fp is None else -fp, r.name)
        return min(cands, key=_key)

    def _requeue(self, rid, rep, cause, attempt: int) -> None:
        telemetry.metrics().counter(
            "serving_handoff_requeue_total").inc()
        telemetry.metrics().counter("gateway_failovers_total",
                                    replica=rep.name).inc()
        flight_recorder.record("handoff_requeue", request_id=rid,
                               replica=rep.name, cause=_cause(cause),
                               attempt=attempt + 1)
        self._backoff(attempt + 1)

    def _backoff(self, attempt: int) -> None:
        delay = min(self.backoff_max,
                    self.backoff_base * 2 ** (attempt - 1))
        with self._lock:
            u = float(self._rng.random())
        _sleep(delay * (1.0 - self.jitter * u))

    def _complete(self, rid, spec: dict, fut: _Future, res) -> None:
        if isinstance(res, Exception):
            res = self._error_result(rid, spec, f"router: {res!r}")
        fut.set(res)

    def _error_result(self, rid, spec: dict, error: str) -> dict:
        return {**spec.get("meta", {}),
                "request_id": rid, "prompt": spec["prompt"],
                "tokens": np.zeros((0,), np.int32), "error": error}

    # -- health -------------------------------------------------------

    def healthz(self) -> dict:
        """Per-pool replica verdicts + the aggregate state:
        ``critical`` with no decode replica alive (nothing can finish
        a request), ``degraded`` with the prefill pool down or any
        replica dead (capacity or the disaggregation benefit is
        reduced), else the worst alive replica's SLO state."""
        rank = {"ok": 0, "degraded": 1, "critical": 2}
        pools, worst = {}, "ok"
        alive = {"prefill": 0, "decode": 0}
        for pool_name, pool in (("prefill", self.prefill),
                                ("decode", self.decode)):
            pools[pool_name] = {}
            for rep in pool:
                h = rep.health()
                pools[pool_name][rep.name] = h
                if h.get("alive"):
                    alive[pool_name] += 1
                    s = h.get("state", "ok")
                    if rank.get(s, 0) > rank[worst]:
                        worst = s
        if alive["decode"] == 0:
            state = "critical"
        elif (alive["prefill"] == 0
              or alive["prefill"] < len(self.prefill)
              or alive["decode"] < len(self.decode)):
            state = worst if rank[worst] >= 1 else "degraded"
        else:
            state = worst
        return {"state": state, "alive": alive,
                "pools": pools}


def _sleep(seconds: float) -> None:
    if seconds > 0:
        import time

        time.sleep(seconds)

"""Evaluators — accuracy-style metrics over a ``Dataset``.

The reference leaned on ``pyspark.ml`` evaluators in notebooks (SURVEY.md
§2.1 Evaluators [LOW]); the rebuild ships its own so the pipeline is
self-contained: an evaluator consumes a prediction column (from
``ModelPredictor``) or runs the model itself, and returns a scalar.
"""

from __future__ import annotations

from typing import Callable, Mapping

import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.predictors import ModelPredictor


class AccuracyEvaluator:
    """Classification accuracy from a prediction column.

    Accepts class-id predictions (int) or logits/probabilities (argmax'd),
    and integer or one-hot label columns (the reference's OneHotTransformer
    workflow produces one-hot labels — mirrored from the one-hot support
    in ops/losses.py).
    """

    def __init__(self, prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        pred = np.asarray(dataset[self.prediction_col])
        if pred.ndim > 1:
            pred = np.argmax(pred, axis=-1)
        labels = np.asarray(dataset[self.label_col])
        if labels.ndim > pred.ndim:
            # a trailing axis of width 1 is a column vector of class ids,
            # not a one-hot encoding — argmaxing it would zero every label
            if labels.shape[-1] > 1:
                labels = np.argmax(labels, axis=-1)
            else:
                labels = np.squeeze(labels, axis=-1)
        if labels.shape != pred.shape:
            raise ValueError(
                f"prediction shape {pred.shape} and label shape "
                f"{labels.shape} do not align")
        return float(np.mean(pred == labels))


class LossEvaluator:
    """Mean of an arbitrary per-row loss ``fn(pred_col_value, label)``."""

    def __init__(self, fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
                 prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.fn = fn
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        return float(np.mean(self.fn(
            np.asarray(dataset[self.prediction_col]),
            np.asarray(dataset[self.label_col]))))


def evaluate_model(model, variables: Mapping, dataset: Dataset, *,
                   features_col: str = "features",
                   label_col: str = "label",
                   batch_size: int = 512) -> dict[str, float]:
    """One-call accuracy for a trained model (predict + evaluate)."""
    predictor = ModelPredictor(model, variables,
                               features_col=features_col,
                               output="class", batch_size=batch_size)
    scored = predictor.predict(dataset)
    acc = AccuracyEvaluator("prediction", label_col).evaluate(scored)
    return {"accuracy": acc}

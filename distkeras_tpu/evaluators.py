"""Evaluators — accuracy-style metrics over a ``Dataset``.

The reference leaned on ``pyspark.ml`` evaluators in notebooks (SURVEY.md
§2.1 Evaluators [LOW]); the rebuild ships its own so the pipeline is
self-contained: an evaluator consumes a prediction column (from
``ModelPredictor``) or runs the model itself, and returns a scalar.
"""

from __future__ import annotations

from typing import Callable, Mapping

import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.predictors import ModelPredictor


class AccuracyEvaluator:
    """Classification accuracy from a prediction column.

    Accepts class-id predictions (int) or logits/probabilities (argmax'd),
    and integer or one-hot label columns (the reference's OneHotTransformer
    workflow produces one-hot labels — mirrored from the one-hot support
    in ops/losses.py).
    """

    def __init__(self, prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        pred = np.asarray(dataset[self.prediction_col])
        if pred.ndim > 1:
            pred = np.argmax(pred, axis=-1)
        labels = np.asarray(dataset[self.label_col])
        if labels.ndim > pred.ndim:
            # a trailing axis of width 1 is a column vector of class ids,
            # not a one-hot encoding — argmaxing it would zero every label
            if labels.shape[-1] > 1:
                labels = np.argmax(labels, axis=-1)
            else:
                labels = np.squeeze(labels, axis=-1)
        if labels.shape != pred.shape:
            raise ValueError(
                f"prediction shape {pred.shape} and label shape "
                f"{labels.shape} do not align")
        return float(np.mean(pred == labels))


class LossEvaluator:
    """Mean of an arbitrary per-row loss ``fn(pred_col_value, label)``."""

    def __init__(self, fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
                 prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.fn = fn
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        return float(np.mean(self.fn(
            np.asarray(dataset[self.prediction_col]),
            np.asarray(dataset[self.label_col]))))


def metrics_from_logits(logits, labels, *,
                        top_k: int | None = None) -> dict[str, float]:
    """Accuracy metrics from raw logits via the jittable ``ops.metrics``
    functions.  Label columns may be integer ids ``[N]``, a column
    vector of ids ``[N, 1]`` (squeezed — argmaxing it would zero every
    label), or one-hot ``[N, C]`` (argmaxed).  Single-logit heads use
    ``binary_accuracy``; ``top_k`` adds ``top{k}_accuracy`` for
    multi-class heads."""
    from distkeras_tpu.ops import metrics as M

    logits = np.asarray(logits)
    labels = np.asarray(labels)
    if labels.ndim == logits.ndim:
        if labels.shape[-1] > 1:
            labels = np.argmax(labels, axis=-1)  # one-hot column
        else:
            labels = np.squeeze(labels, axis=-1)  # column vector of ids
    if logits.shape[-1] == 1:
        return {"accuracy": float(M.binary_accuracy(logits, labels))}
    out = {"accuracy": float(M.accuracy(logits, labels))}
    if top_k is not None and logits.shape[-1] > top_k:
        out[f"top{top_k}_accuracy"] = float(
            M.top_k_accuracy(logits, labels, k=top_k))
    return out


def evaluate_model(model, variables: Mapping, dataset: Dataset, *,
                   features_col: str = "features",
                   label_col: str = "label",
                   batch_size: int = 512,
                   top_k: int | None = None) -> dict[str, float]:
    """One-call evaluation for a trained model: sharded batch inference
    to logits, then ``metrics_from_logits``."""
    predictor = ModelPredictor(model, variables,
                               features_col=features_col,
                               output="logits", batch_size=batch_size)
    scored = predictor.predict(dataset)
    return metrics_from_logits(scored["prediction"],
                               dataset[label_col], top_k=top_k)

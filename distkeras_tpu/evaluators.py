"""Evaluators — accuracy-style metrics over a ``Dataset``.

The reference leaned on ``pyspark.ml`` evaluators in notebooks (SURVEY.md
§2.1 Evaluators [LOW]); the rebuild ships its own so the pipeline is
self-contained: an evaluator consumes a prediction column (from
``ModelPredictor``) or runs the model itself, and returns a scalar.
"""

from __future__ import annotations

import re

from typing import Callable, Mapping

import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.predictors import ModelPredictor


def _normalize_class_labels(labels: np.ndarray) -> np.ndarray:
    """Class ids from a label column that may be integer ids ``[N]``, a
    column vector of ids ``[N, 1]`` (squeezed — argmaxing it would zero
    every label), or one-hot rows ``[N, C]`` (argmax'd; the reference's
    OneHotTransformer workflow)."""
    if labels.ndim > 1:
        if labels.shape[-1] > 1:
            labels = np.argmax(labels, axis=-1)
        else:
            labels = np.squeeze(labels, axis=-1)
    return labels


def _aligned_pred_labels(dataset: Dataset, prediction_col: str,
                         label_col: str) -> tuple[np.ndarray, np.ndarray]:
    """Class-id (pred, labels) from a scored dataset.  Predictions may
    be class ids (int) or logits/probabilities (argmax'd); labels may be
    integer ids, a column vector of ids (squeezed — argmaxing it would
    zero every label), or one-hot rows (argmax'd; the reference's
    OneHotTransformer workflow — mirrored from ops/losses.py)."""
    pred = np.asarray(dataset[prediction_col])
    if pred.ndim > 1:
        # same width-1 trap as the label side: an [N, 1] column vector
        # of class ids must be squeezed, not argmax'd to all-zeros
        if pred.shape[-1] > 1:
            pred = np.argmax(pred, axis=-1)
        else:
            pred = np.squeeze(pred, axis=-1)
    labels = np.asarray(dataset[label_col])
    if labels.ndim > pred.ndim:
        labels = _normalize_class_labels(labels)
    if np.issubdtype(pred.dtype, np.floating):
        # a float prediction column that isn't integral class ids is a
        # score column (e.g. a single-logit binary model): comparing it
        # raw against labels would silently return ~0 accuracy
        if pred.size and not np.array_equal(pred, np.round(pred)):
            raise ValueError(
                f"prediction column {prediction_col!r} holds "
                f"non-integral float scores, not class ids; for "
                f"one-score-per-row binary outputs use "
                f"BinaryClassificationEvaluator (or argmax multi-class "
                f"scores into class ids first)")
        pred = pred.astype(np.int64)
    if labels.shape != pred.shape:
        raise ValueError(
            f"prediction shape {pred.shape} and label shape "
            f"{labels.shape} do not align")
    return pred, labels


class AccuracyEvaluator:
    """Classification accuracy from a prediction column (input handling
    in ``_aligned_pred_labels``)."""

    def __init__(self, prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        pred, labels = _aligned_pred_labels(
            dataset, self.prediction_col, self.label_col)
        return float(np.mean(pred == labels))


class ClassificationEvaluator:
    """Multi-class precision / recall / F1 / accuracy over a scored
    dataset — the ``pyspark.ml`` ``MulticlassClassificationEvaluator``
    analogue the reference notebooks used (SURVEY.md §2.1 Evaluators).

    ``metric``: ``'f1'`` (default, like pyspark), ``'precision'``,
    ``'recall'``, ``'accuracy'``, or ``'auc'`` (one-vs-rest macro
    AUC-ROC via ``ops.metrics.macro_auc_roc`` — needs the prediction
    column to hold per-class scores ``[N, C]``, not argmax'd class
    ids); ``average`` as in ``ops.metrics.precision_recall_f1``
    (``'auc'`` supports ``'macro'`` only).  ``num_classes`` is inferred
    from the data (max id + 1, or the score width for ``'auc'``) when
    not given — except for ``average='macro'`` on the count-based
    metrics, whose denominator is the class count itself: there an
    explicit ``num_classes`` is required, otherwise the score would
    silently depend on which classes happen to appear in the evaluated
    split.
    """

    def __init__(self, metric: str = "f1", average: str = "weighted",
                 prediction_col: str = "prediction",
                 label_col: str = "label",
                 num_classes: int | None = None):
        if metric not in ("f1", "precision", "recall", "accuracy",
                          "auc"):
            raise ValueError(
                f"unknown metric {metric!r}; expected 'f1', "
                f"'precision', 'recall', 'accuracy', or 'auc'")
        if metric == "auc":
            if average != "macro":
                raise ValueError(
                    f"metric='auc' supports average='macro' only "
                    f"(one-vs-rest), got {average!r}")
        elif average not in ("weighted", "macro", "micro"):
            raise ValueError(
                f"unknown average {average!r}; expected 'weighted', "
                f"'macro', or 'micro'")
        if average == "macro" and num_classes is None \
                and metric not in ("accuracy", "auc"):
            raise ValueError(
                "average='macro' needs an explicit num_classes (its "
                "denominator is the class count; inferring it from "
                "the evaluated split would make the score depend on "
                "which classes happen to appear)")
        self.metric = metric
        self.average = average
        self.prediction_col = prediction_col
        self.label_col = label_col
        self.num_classes = num_classes

    def evaluate(self, dataset: Dataset) -> float:
        from distkeras_tpu.ops.metrics import (macro_auc_roc,
                                               precision_recall_f1)

        if self.metric == "auc":
            scores = np.asarray(dataset[self.prediction_col])
            if scores.ndim != 2 or scores.shape[-1] < 2:
                raise ValueError(
                    f"metric='auc' needs per-class scores [N, C] in "
                    f"{self.prediction_col!r} (run ModelPredictor with "
                    f"output='logits'), got shape {scores.shape}")
            labels = _normalize_class_labels(
                np.asarray(dataset[self.label_col]))
            if scores.size == 0:
                raise ValueError("cannot evaluate an empty dataset")
            return float(macro_auc_roc(
                scores, labels, num_classes=self.num_classes))

        pred, labels = _aligned_pred_labels(
            dataset, self.prediction_col, self.label_col)
        if pred.size == 0:
            raise ValueError("cannot evaluate an empty dataset")
        if self.metric == "accuracy":
            return float(np.mean(pred == labels))
        n = self.num_classes or int(max(pred.max(), labels.max())) + 1
        scores = precision_recall_f1(pred, labels, num_classes=n,
                                     average=self.average)
        return float(scores[self.metric])


class BinaryClassificationEvaluator:
    """AUC-ROC (default) or accuracy over a scored dataset with a
    single score per row — the ``pyspark.ml``
    ``BinaryClassificationEvaluator`` analogue for the Criteo-style
    binary configs.  The prediction column may be ``[N]`` or ``[N, 1]``
    logits/probabilities (any monotone ranking gives the same AUC);
    labels in {0, 1}."""

    def __init__(self, metric: str = "auc",
                 prediction_col: str = "prediction",
                 label_col: str = "label",
                 threshold: float | None = None):
        """``threshold`` only affects ``metric='accuracy'``: scores
        above it classify as 1 (0.0 suits logits; use 0.5 for
        probabilities).  When not given it defaults to 0.0 — but if
        every score lies in [0, 1] (probability-shaped, where 0.0 would
        classify everything as class 1 and silently return the base
        rate), ``evaluate`` demands an explicit threshold instead of
        guessing.  AUC is threshold-free."""
        if metric not in ("auc", "accuracy"):
            raise ValueError(f"unknown metric {metric!r}; expected "
                             f"'auc' or 'accuracy'")
        self.metric = metric
        self.prediction_col = prediction_col
        self.label_col = label_col
        self._threshold_given = threshold is not None
        self.threshold = 0.0 if threshold is None else float(threshold)

    def evaluate(self, dataset: Dataset) -> float:
        from distkeras_tpu.ops.metrics import auc_roc, binary_accuracy

        scores = np.asarray(dataset[self.prediction_col])
        if scores.ndim > 1:
            if scores.shape[-1] != 1:
                raise ValueError(
                    f"binary evaluation needs one score per row, got "
                    f"shape {scores.shape}")
            scores = np.squeeze(scores, axis=-1)
        labels = np.asarray(dataset[self.label_col]).reshape(-1)
        if scores.shape != labels.shape:
            raise ValueError(
                f"score shape {scores.shape} and label shape "
                f"{labels.shape} do not align")
        if scores.size == 0:
            raise ValueError("cannot evaluate an empty dataset")
        if self.metric == "accuracy":
            if not self._threshold_given and scores.min() >= 0.0 \
                    and scores.max() <= 1.0:
                raise ValueError(
                    "all scores lie in [0, 1] (probability-shaped); "
                    "the default threshold 0.0 would classify every "
                    "row as class 1.  Pass threshold=0.5 for "
                    "probabilities (or threshold=0.0 explicitly for "
                    "logits that happen to land in [0, 1])")
            return float(binary_accuracy(scores - self.threshold,
                                         labels))
        return float(auc_roc(scores, labels))


class LossEvaluator:
    """Mean of an arbitrary per-row loss ``fn(pred_col_value, label)``."""

    def __init__(self, fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
                 prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.fn = fn
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        return float(np.mean(self.fn(
            np.asarray(dataset[self.prediction_col]),
            np.asarray(dataset[self.label_col]))))


def metrics_from_logits(logits, labels, *,
                        top_k: int | None = None) -> dict[str, float]:
    """Accuracy metrics from raw logits via the jittable ``ops.metrics``
    functions.  Label columns may be integer ids ``[N]``, a column
    vector of ids ``[N, 1]`` (squeezed — argmaxing it would zero every
    label), or one-hot ``[N, C]`` (argmaxed).  Single-logit heads use
    ``binary_accuracy``; ``top_k`` adds ``top{k}_accuracy`` for
    multi-class heads."""
    from distkeras_tpu.ops import metrics as M

    logits = np.asarray(logits)
    labels = np.asarray(labels)
    if labels.ndim == logits.ndim:
        labels = _normalize_class_labels(labels)
    if logits.shape[-1] == 1:
        return {"accuracy": float(M.binary_accuracy(logits, labels))}
    out = {"accuracy": float(M.accuracy(logits, labels))}
    if top_k is not None and logits.shape[-1] > top_k:
        out[f"top{top_k}_accuracy"] = float(
            M.top_k_accuracy(logits, labels, k=top_k))
    return out


def evaluate_model(model, variables: Mapping, dataset: Dataset, *,
                   features_col: str = "features",
                   label_col="label",
                   batch_size: int = 512,
                   top_k: int | None = None) -> dict:
    """One-call evaluation for a trained model: sharded batch inference
    to logits, then ``metrics_from_logits``.

    Multi-OUTPUT models (e.g. an ingested two-head keras DAG): pass
    ``label_col`` as a sequence naming one label column per head, in
    the model's output order — returns ``{label_col: metrics}`` per
    head instead of one flat metrics dict.  A multi-output model with
    a scalar ``label_col`` still fails loudly (silently scoring head 0
    against the only label would be the reference's kind of quiet
    wrong answer)."""
    predictor = ModelPredictor(model, variables,
                               features_col=features_col,
                               output="logits", batch_size=batch_size)
    multi = isinstance(label_col, (list, tuple))
    if (not multi and predictor.spec is not None and len(
            predictor.spec.kwargs.get("outputs", ())) > 1):
        # known multi-output spec: refuse before paying the inference
        raise NotImplementedError(
            "evaluate_model with a scalar label_col needs a "
            "single-output model; this spec has "
            f"{len(predictor.spec.kwargs['outputs'])} heads — pass "
            "label_col=[...] naming one label column per head (in "
            "output order) to evaluate them all")
    scored = predictor.predict(dataset)
    if multi:
        if "prediction" in scored.column_names:  # single-head model
            if len(label_col) == 1:
                return {label_col[0]: metrics_from_logits(
                    scored["prediction"], dataset[label_col[0]],
                    top_k=top_k)}
            raise ValueError(
                f"label_col={list(label_col)} names "
                f"{len(label_col)} heads but the model has 1")
        # Count exactly the columns the predictor APPENDS: contiguous
        # prediction_0..prediction_{n-1}.  A user dataset that already
        # carries its own prediction_*-named columns (the predictor
        # keeps input columns) must not inflate the head count
        # (ADVICE.md r5).
        numbered = {int(m.group(1)) for c in scored.column_names
                    if (m := re.fullmatch(r"prediction_(\d+)", c))}
        n_heads = 0
        while n_heads in numbered:
            n_heads += 1
        if n_heads != len(label_col):
            # a head-count mismatch in EITHER direction is loud —
            # silently scoring the first len(label_col) heads would be
            # exactly the quiet wrong answer this guard exists for
            raise ValueError(
                f"label_col={list(label_col)} names "
                f"{len(label_col)} heads but the model produced "
                f"{n_heads} — pass exactly one label column per "
                "head, in output order")
        heads = [f"prediction_{i}" for i in range(len(label_col))]
        return {lab: metrics_from_logits(scored[h], dataset[lab],
                                         top_k=top_k)
                for h, lab in zip(heads, label_col)}
    if "prediction" not in scored.column_names:
        raise NotImplementedError(
            "evaluate_model with a scalar label_col needs a "
            "single-output model; this model produced columns "
            f"{sorted(scored.column_names)} — pass label_col=[...] "
            "naming one label column per head (in output order)")
    return metrics_from_logits(scored["prediction"],
                               dataset[label_col], top_k=top_k)

"""Surface-drift lint (ISSUE 9 tentpole, pass 3 of 3).

AST-extracts every externally visible *name* the runtime emits —
telemetry metric names (``counter/gauge/histogram`` first args), span
and instant names, flight-recorder event kinds, SLO signal names
(``DEFAULT_SLO_THRESHOLDS`` keys), trainer history keys (keyword args
of ``self._record(...)``), and single-byte wire opcodes in the wire
modules — then cross-checks them against ``docs/API.md`` and the
``transport.WIRE_OPS`` registry.  A renamed emission therefore breaks
the lint, not just the docs; an opcode literal that is not registered
(or is registered under a different protocol scope) is an error.

``tests/test_history_keys.py`` builds on the same extractor, so the
test and the lint can never disagree about what the surface is.
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field

from . import Finding

RULE_METRIC = "undocumented-metric"
RULE_SPAN = "undocumented-span"
RULE_FLIGHT = "undocumented-flight-kind"
RULE_SLO = "undocumented-slo-signal"
RULE_HISTORY = "undocumented-history-key"
RULE_TIER = "undocumented-tier"
RULE_OPCODE = "unregistered-opcode"

#: wire modules and the WIRE_OPS protocol scope their byte literals
#: belong to (transport itself only carries the frame-level trace tag)
WIRE_SCOPES = {
    "distkeras_tpu/parallel/host_ps.py": "ps",
    "distkeras_tpu/parallel/sharded_ps.py": "ps",
    "distkeras_tpu/parallel/replicated_ps.py": "repl",
    "distkeras_tpu/parallel/elastic_ps.py": "elastic",
    "distkeras_tpu/parallel/hier_ps.py": "hier",
    "distkeras_tpu/gateway.py": "replica",
    "distkeras_tpu/serving.py": "kv",
    "distkeras_tpu/parallel/transport.py": "frame",
}

_Site = tuple[str, int]  # (path, line)


@dataclass
class Surface:
    """Everything the package emits, each name -> first site seen."""

    metrics: dict[str, _Site] = field(default_factory=dict)
    spans: dict[str, _Site] = field(default_factory=dict)
    flight_kinds: dict[str, _Site] = field(default_factory=dict)
    slo_signals: dict[str, _Site] = field(default_factory=dict)
    history_keys: dict[str, _Site] = field(default_factory=dict)
    #: ``fidelity=`` lowering tiers (``TIERS`` registry keys)
    tiers: dict[str, _Site] = field(default_factory=dict)
    # scope -> opcode byte -> site
    wire_ops: dict[str, dict[bytes, _Site]] = field(
        default_factory=dict)

    def merge(self, other: "Surface") -> None:
        for name in ("metrics", "spans", "flight_kinds",
                     "slo_signals", "history_keys", "tiers"):
            mine, theirs = getattr(self, name), getattr(other, name)
            for k, site in theirs.items():
                mine.setdefault(k, site)
        for scope, ops in other.wire_ops.items():
            mine = self.wire_ops.setdefault(scope, {})
            for op, site in ops.items():
                mine.setdefault(op, site)


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_arg0(call: ast.Call) -> str | None:
    if (call.args and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)):
        return call.args[0].value
    return None


def extract_source(src: str, path: str,
                   wire_scope: str | None = None) -> Surface:
    """Extract the emission surface of one module's source text."""
    s = Surface()
    tree = ast.parse(src, filename=path)
    if wire_scope is None:
        wire_scope = WIRE_SCOPES.get(path)
    # registry registrations are definitions, not uses: their byte
    # literals are exempt from the wire-op scan
    registration_consts: set[int] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and (_dotted(node.func) or "").endswith(
                    "WIRE_OPS.register")):
            registration_consts.update(
                id(a) for a in node.args
                if isinstance(a, ast.Constant))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            _extract_call(node, path, s)
        elif (isinstance(node, ast.Assign)
              and any(isinstance(t, ast.Name)
                      and t.id == "DEFAULT_SLO_THRESHOLDS"
                      for t in node.targets)
              and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant):
                    s.slo_signals.setdefault(
                        k.value, (path, k.lineno))
        elif (isinstance(node, ast.Assign)
              and any(isinstance(t, ast.Name) and t.id == "TIERS"
                      for t in node.targets)
              and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant):
                    s.tiers.setdefault(k.value, (path, k.lineno))
        elif (wire_scope is not None
              and isinstance(node, ast.Constant)
              and isinstance(node.value, bytes)
              and len(node.value) == 1
              and id(node) not in registration_consts):
            s.wire_ops.setdefault(wire_scope, {}).setdefault(
                node.value, (path, node.lineno))
    return s


def _extract_call(call: ast.Call, path: str, s: Surface) -> None:
    func = call.func
    meth = func.attr if isinstance(func, ast.Attribute) else None
    d = _dotted(func)
    site = (path, call.lineno)
    if meth in ("counter", "gauge", "histogram"):
        name = _str_arg0(call)
        if name:
            s.metrics.setdefault(name, site)
    elif meth in ("span", "instant", "complete") or (
            d in ("span", "instant", "complete")):
        name = _str_arg0(call)
        if name:
            s.spans.setdefault(name, site)
    elif d is not None and d.endswith("flight_recorder.record"):
        name = _str_arg0(call)
        if name:
            s.flight_kinds.setdefault(name, site)
    elif d is not None and d.endswith("._record"):
        for kw in call.keywords:
            if kw.arg:
                s.history_keys.setdefault(kw.arg, site)


def extract_paths(repo_root: pathlib.Path,
                  paths: list[pathlib.Path]) -> Surface:
    s = Surface()
    for p in paths:
        rel = p.relative_to(repo_root).as_posix()
        s.merge(extract_source(p.read_text(), rel))
    return s


# -- docs cross-checks -------------------------------------------------


def _word_in(name: str, text: str) -> bool:
    return re.search(
        rf"(?<![A-Za-z0-9_]){re.escape(name)}(?![A-Za-z0-9_])",
        text) is not None


def _table_rows(docs: str) -> set[str]:
    """All first-column backticked identifiers of any docs table."""
    return set(re.findall(r"^\| `([A-Za-z_]\w*)` \|", docs, re.M))


def documented_history_keys(docs: str) -> set[str]:
    """First-column keys of the 'Trainer history keys' table (the
    parser ``tests/test_history_keys.py`` shares)."""
    m = re.search(r"### Trainer history keys(.*?)(?:\n## |\Z)",
                  docs, re.S)
    if not m:
        return set()
    return set(re.findall(r"^\| `([a-z_]+)` \|", m.group(1), re.M))


def documented_tiers(docs: str) -> set[str]:
    """First-column names of the 'Lowering tiers' table."""
    m = re.search(r"### Lowering tiers(.*?)(?:\n## |\Z)", docs, re.S)
    if not m:
        return set()
    return set(re.findall(r"^\| `([a-z_]+)` \|", m.group(1), re.M))


def check_docs(surface: Surface, docs: str) -> list[Finding]:
    """Every extracted name must appear in docs/API.md: metrics and
    span names anywhere as a whole word, flight kinds and SLO signals
    as table rows, history keys as rows of the history-key table,
    lowering tiers as rows of the 'Lowering tiers' table."""
    out: list[Finding] = []
    rows = _table_rows(docs)
    hist = documented_history_keys(docs)
    tier_rows = documented_tiers(docs)
    for name, (path, line) in sorted(surface.metrics.items()):
        if not _word_in(name, docs):
            out.append(Finding(
                RULE_METRIC, path, line,
                f"metric {name!r} emitted but absent from "
                f"docs/API.md"))
    for name, (path, line) in sorted(surface.spans.items()):
        if not _word_in(name, docs):
            out.append(Finding(
                RULE_SPAN, path, line,
                f"span/instant {name!r} emitted but absent from "
                f"docs/API.md"))
    for name, (path, line) in sorted(surface.flight_kinds.items()):
        if name not in rows:
            out.append(Finding(
                RULE_FLIGHT, path, line,
                f"flight-recorder kind {name!r} emitted but has no "
                f"row in the docs/API.md kind table"))
    for name, (path, line) in sorted(surface.slo_signals.items()):
        if name not in rows:
            out.append(Finding(
                RULE_SLO, path, line,
                f"SLO signal {name!r} defined but has no row in the "
                f"docs/API.md threshold table"))
    for name, (path, line) in sorted(surface.history_keys.items()):
        if name not in hist:
            out.append(Finding(
                RULE_HISTORY, path, line,
                f"history key {name!r} recorded but missing from the "
                f"docs/API.md 'Trainer history keys' table"))
    for name, (path, line) in sorted(surface.tiers.items()):
        if name not in tier_rows:
            out.append(Finding(
                RULE_TIER, path, line,
                f"lowering tier {name!r} registered but has no row "
                f"in the docs/API.md 'Lowering tiers' table"))
    return out


def check_opcodes(surface: Surface, registry=None) -> list[Finding]:
    """Every single-byte literal in a wire module must be registered in
    ``transport.WIRE_OPS`` under that module's protocol scope."""
    if registry is None:
        from distkeras_tpu.parallel.transport import WIRE_OPS
        registry = WIRE_OPS
    out: list[Finding] = []
    for scope, ops in sorted(surface.wire_ops.items()):
        known = registry.ops(scope)
        for op, (path, line) in sorted(ops.items()):
            if op not in known:
                out.append(Finding(
                    RULE_OPCODE, path, line,
                    f"wire byte {op!r} used in scope {scope!r} but "
                    f"not registered in transport.WIRE_OPS"))
    return out


def check_all(repo_root: pathlib.Path, paths: list[pathlib.Path],
              docs_path: pathlib.Path | None = None) -> list[Finding]:
    surface = extract_paths(repo_root, paths)
    docs_path = docs_path or repo_root / "docs/API.md"
    findings = check_docs(surface, docs_path.read_text())
    findings += check_opcodes(surface)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))

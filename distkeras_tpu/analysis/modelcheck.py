"""Deterministic cooperative scheduler + exhaustive interleaving
explorer for protocol models (ISSUE 11 tentpole).

This is the CHESS/DPOR shape applied to our own stack: protocol
participants are GENERATOR-based actors that yield at labeled decision
points; the explorer enumerates every schedule up to a bound, asserts
safety invariants in every reached state, and reports any violation as
a minimized schedule trace that replays byte-for-byte.

Actor API
---------
An actor is a generator function ``def actor(ctx): ...`` registered on
a :class:`Model`.  It runs ATOMICALLY between yields; every yield is a
labeled decision point the scheduler owns:

* ``yield Step("label")``         — plain scheduling point (the actor
  is re-enabled immediately; the step's world mutations happened
  before the yield).
* ``x = yield Choose("label", options)`` — internal nondeterminism;
  the explorer forks one branch per option and sends the chosen value
  back into the generator.
* ``msg = yield Recv("chan")``    — blocks until the named channel is
  nonempty, then receives its head (channels are FIFO per key; the
  nondeterminism between channels comes from WHICH actor the
  scheduler runs, so per-pair FIFO order is preserved like TCP).
* ``yield Timer("label")``        — fires only when the scheduler
  chooses this actor AND the model's timer budget allows it; models
  timeouts (election timers) without wall clocks.

Within an atomic step the actor mutates the shared ``world`` object
and calls ``ctx.send(chan, msg)`` freely.  Discipline: ALL protocol
state lives in ``world`` (fingerprinted for state-hash dedup);
generator locals only drive control flow.

Crashes are explorer-level transitions on actors declared
``crashable``: the explorer may, at any scheduling point while the
crash budget lasts, kill the actor and invoke the model's
``on_crash`` hook to mutate the world.

Exploration
-----------
Generators cannot be cloned, so the explorer is REPLAY-based: to
explore a sibling branch it rebuilds the initial world from the model
factory and re-executes the schedule prefix — O(depth) per branch,
the standard stateless-model-checking trade (Godefroot's VeriSoft).
DFS is bounded by ``max_depth`` and a CHESS-style preemption budget
(``max_preemptions``: unforced actor switches).  Visited states are
deduplicated by ``(world.fingerprint(), per-actor program position)``.
Partial-order reduction: transitions may declare static footprints
(sets of world-resource keys); at each state, transitions whose
footprints are disjoint from every other enabled transition's are
explored as a singleton (persistent set of one), and a sleep-set pass
prunes re-exploration of commutative siblings.

Violations come back as :class:`Violation` with a schedule string —
space-joined transition tokens — that :meth:`Explorer.replay`
re-executes deterministically; ``minimize`` then BFSes for the
shortest violating schedule.

Telemetry: ``modelcheck_states_explored_total`` and
``modelcheck_violations_total{invariant=...}`` counters on the global
registry (``scripts/check_protocol.py --metrics-out`` snapshots them
for ``perf_regress --from-registry``).
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

from distkeras_tpu import telemetry

# ---------------------------------------------------------------------
# decision-point ops (yielded by actors)


def _token_label(label) -> str:
    """Labels become schedule-string tokens, so they must survive a
    whitespace split-and-rejoin byte-for-byte."""
    return re.sub(r"\s+", "", str(label))


class Op:
    """Base decision point; subclasses carry the scheduling payload."""

    label: str
    #: static footprint: world-resource keys this step may touch, or
    #: None for "dependent with everything" (the safe default)
    footprint: Optional[frozenset] = None


class Step(Op):
    """Plain labeled scheduling point."""

    def __init__(self, label: str, footprint: Optional[Iterable] = None):
        self.label = _token_label(label)
        self.footprint = (frozenset(footprint)
                          if footprint is not None else None)

    def __repr__(self):
        return f"Step({self.label!r})"


class Choose(Op):
    """Internal nondeterminism: the explorer forks one branch per
    option and sends the chosen option back into the generator."""

    def __init__(self, label: str, options: Iterable):
        self.label = _token_label(label)
        self.options = list(options)
        if not self.options:
            raise ValueError(f"Choose({label!r}) with no options")

    def __repr__(self):
        return f"Choose({self.label!r}, {self.options!r})"


class Recv(Op):
    """Receive the head of a FIFO channel; blocks (actor disabled)
    while the channel is empty."""

    def __init__(self, chan, footprint: Optional[Iterable] = None):
        self.chan = chan
        self.label = _token_label(f"recv:{chan!r}")
        self.footprint = (frozenset(footprint)
                          if footprint is not None else None)

    def __repr__(self):
        return f"Recv({self.chan!r})"


class Timer(Op):
    """A timeout that fires only when the scheduler picks it and the
    model's timer budget allows; never fires otherwise (models 'the
    timer MAY fire now' without wall clocks)."""

    def __init__(self, label: str):
        self.label = _token_label(label)

    def __repr__(self):
        return f"Timer({self.label!r})"


# ---------------------------------------------------------------------
# runtime context handed to actors


class Context:
    """Actor-facing handle on the world: shared state + channels."""

    def __init__(self, world):
        self.world = world
        self._channels: dict[Any, list] = {}

    def send(self, chan, msg) -> None:
        """Append ``msg`` to channel ``chan`` (FIFO per channel)."""
        self._channels.setdefault(chan, []).append(msg)

    def pending(self, chan) -> int:
        return len(self._channels.get(chan, ()))

    def drain(self, chan) -> list:
        """Drop every queued message on ``chan`` (link down / crash)."""
        msgs = self._channels.pop(chan, [])
        return msgs

    def _chan_fingerprint(self):
        return tuple(sorted(
            (repr(k), tuple(repr(m) for m in v))
            for k, v in self._channels.items() if v))


# ---------------------------------------------------------------------
# model + violation containers


@dataclass
class Invariant:
    name: str
    check: Callable[[Any], Optional[str]]  # world -> error or None


@dataclass
class Violation(Exception):
    invariant: str
    detail: str
    schedule: str
    depth: int

    def __str__(self):
        return (f"invariant {self.invariant!r} violated at depth "
                f"{self.depth}: {self.detail}\n  schedule: "
                f"{self.schedule}")


class Model:
    """A checkable protocol instance: a world factory, actors, and
    invariants.  ``make_world()`` must be deterministic — replay
    correctness depends on it."""

    def __init__(self, make_world: Callable[[], Any]):
        self.make_world = make_world
        self.actors: list[tuple[str, Callable]] = []
        self.invariants: list[Invariant] = []
        self.crashable: dict[str, Callable] = {}
        self.timer_budget: int = 0
        self.crash_budget: int = 0

    def actor(self, name: str, fn: Callable) -> "Model":
        self.actors.append((str(name), fn))
        return self

    def invariant(self, name: str, check: Callable) -> "Model":
        self.invariants.append(Invariant(str(name), check))
        return self

    def allow_crash(self, name: str, on_crash: Callable,
                    budget: int = 1) -> "Model":
        """Declare actor ``name`` crashable; ``on_crash(ctx)`` runs
        when the explorer kills it (the ctx lets it mutate the world
        AND drain the dead actor's channels).  ``budget`` is shared
        across all crashable actors per execution."""
        self.crashable[str(name)] = on_crash
        self.crash_budget = max(self.crash_budget, int(budget))
        return self


# ---------------------------------------------------------------------
# a single deterministic execution


@dataclass
class _ActorState:
    name: str
    gen: Any
    op: Optional[Op]  # current pending decision point; None = done
    crashed: bool = False


class _Execution:
    """One run of the model: actors started, stepped by transition
    token.  The explorer drives it; ``replay`` re-drives it."""

    def __init__(self, model: Model):
        self.model = model
        self.world = model.make_world()
        self.ctx = Context(self.world)
        self.timer_budget = int(model.timer_budget)
        self.crash_budget = int(model.crash_budget)
        self.actors: dict[str, _ActorState] = {}
        for name, fn in model.actors:
            gen = fn(self.ctx)
            st = _ActorState(name, gen, None)
            self.actors[name] = st
            self._advance(st, None, first=True)

    # -- stepping ------------------------------------------------------

    def _advance(self, st: _ActorState, send_value,
                 first: bool = False) -> None:
        """Run the actor's next atomic step, parking it at its next
        decision point (or marking it done)."""
        try:
            op = (next(st.gen) if first
                  else st.gen.send(send_value))
        except StopIteration:
            st.op = None
            return
        if not isinstance(op, Op):
            raise TypeError(f"actor {st.name!r} yielded {op!r}; "
                            "expected a modelcheck.Op")
        st.op = op

    def enabled(self) -> list[str]:
        """Sorted transition tokens enabled in the current state.

        Token grammar (stable — schedules are strings of these):
          ``<actor>/<label>``            run a Step/Timer/Recv
          ``<actor>/<label>=<i>``        resolve a Choose with option i
          ``crash:<actor>``              kill a crashable actor
        """
        toks = []
        for name, st in sorted(self.actors.items()):
            if st.crashed or st.op is None:
                continue
            op = st.op
            if isinstance(op, Choose):
                for i in range(len(op.options)):
                    toks.append(f"{name}/{op.label}={i}")
            elif isinstance(op, Recv):
                if self.ctx.pending(op.chan):
                    toks.append(f"{name}/{op.label}")
            elif isinstance(op, Timer):
                if self.timer_budget > 0:
                    toks.append(f"{name}/{op.label}")
            else:
                toks.append(f"{name}/{op.label}")
            if (st.name in self.model.crashable
                    and self.crash_budget > 0):
                toks.append(f"crash:{name}")
        return sorted(set(toks))

    def footprint_of(self, token: str) -> Optional[frozenset]:
        """Static footprint of an enabled transition, or None for
        'dependent with everything'."""
        if token.startswith("crash:"):
            return None
        name = token.split("/", 1)[0]
        st = self.actors.get(name)
        if st is None or st.op is None:
            return None
        if isinstance(st.op, (Choose, Timer)):
            return None
        return st.op.footprint

    def step(self, token: str) -> None:
        """Execute one transition token (must be in ``enabled()``)."""
        if token.startswith("crash:"):
            name = token[len("crash:"):]
            st = self.actors[name]
            if st.crashed or name not in self.model.crashable:
                raise KeyError(f"cannot crash {name!r}")
            if self.crash_budget <= 0:
                raise KeyError("crash budget exhausted")
            self.crash_budget -= 1
            st.crashed = True
            st.op = None
            st.gen.close()
            self.model.crashable[name](self.ctx)
            return
        name, rest = token.split("/", 1)
        st = self.actors[name]
        op = st.op
        if op is None or st.crashed:
            raise KeyError(f"{token!r} not enabled (actor parked)")
        if isinstance(op, Choose):
            label, _, idx = rest.rpartition("=")
            if label != op.label:
                raise KeyError(f"{token!r}: actor is at {op.label!r}")
            self._advance(st, op.options[int(idx)])
        elif isinstance(op, Recv):
            if rest != op.label or not self.ctx.pending(op.chan):
                raise KeyError(f"{token!r} not enabled")
            msg = self.ctx._channels[op.chan].pop(0)
            if not self.ctx._channels[op.chan]:
                del self.ctx._channels[op.chan]
            self._advance(st, msg)
        elif isinstance(op, Timer):
            if rest != op.label or self.timer_budget <= 0:
                raise KeyError(f"{token!r} not enabled")
            self.timer_budget -= 1
            self._advance(st, None)
        else:
            if rest != op.label:
                raise KeyError(f"{token!r}: actor is at {op.label!r}")
            self._advance(st, None)

    # -- state identity ------------------------------------------------

    def fingerprint(self) -> str:
        """Hash of (world, channels, per-actor position, budgets) —
        the state-dedup key."""
        parts = [repr(self.world.fingerprint()),
                 repr(self.ctx._chan_fingerprint()),
                 f"t={self.timer_budget}", f"c={self.crash_budget}"]
        for name, st in sorted(self.actors.items()):
            parts.append(f"{name}:{'X' if st.crashed else ''}"
                         f"{st.op!r}")
        return hashlib.sha1(
            "\x00".join(parts).encode()).hexdigest()

    def check_invariants(self) -> Optional[tuple[str, str]]:
        for inv in self.model.invariants:
            err = inv.check(self.world)
            if err:
                return inv.name, str(err)
        return None


# ---------------------------------------------------------------------
# explorer


@dataclass
class Report:
    states: int
    executions: int
    truncated: int
    violation: Optional[Violation] = None
    pruned_sleep: int = 0
    pruned_dedup: int = 0


class Explorer:
    """Bounded DFS over interleavings with state dedup + POR."""

    def __init__(self, model: Model, *, max_depth: int = 24,
                 max_preemptions: Optional[int] = None,
                 max_states: int = 2_000_000):
        self.model = model
        self.max_depth = int(max_depth)
        self.max_preemptions = (None if max_preemptions is None
                                else int(max_preemptions))
        self.max_states = int(max_states)

    # -- replay --------------------------------------------------------

    def _exec_prefix(self, prefix: list[str]) -> _Execution:
        ex = _Execution(self.model)
        for tok in prefix:
            ex.step(tok)
        return ex

    def replay(self, schedule: str) -> Optional[Violation]:
        """Re-execute a schedule string deterministically, checking
        invariants after every transition; returns the Violation it
        reproduces (or None if the schedule runs clean — i.e. the
        counterexample does NOT replay)."""
        toks = schedule.split()
        ex = _Execution(self.model)
        bad = ex.check_invariants()
        for i, tok in enumerate(toks):
            if tok not in ex.enabled():
                raise KeyError(
                    f"replay: {tok!r} not enabled at step {i} "
                    f"(enabled: {ex.enabled()})")
            ex.step(tok)
            bad = ex.check_invariants()
            if bad:
                return Violation(bad[0], bad[1],
                                 " ".join(toks[:i + 1]), i + 1)
        return None

    # -- exploration ---------------------------------------------------

    def run(self) -> Report:
        """Bounded DFS.  Returns a Report; ``report.violation`` is the
        MINIMIZED, replay-verified counterexample if one exists."""
        reg = telemetry.metrics()
        states = reg.counter("modelcheck_states_explored_total")
        rep = Report(states=0, executions=0, truncated=0)
        visited: set[str] = set()

        def actor_of(tok: str) -> str:
            if tok.startswith("crash:"):
                return tok[len("crash:"):]
            return tok.split("/", 1)[0]

        # stack entries: (prefix, sleep-set, last-actor, preemptions)
        stack: list[tuple[list[str], frozenset, Optional[str], int]]
        stack = [([], frozenset(), None, 0)]
        found: Optional[Violation] = None
        while stack and found is None:
            prefix, sleep, last, preempt = stack.pop()
            ex = self._exec_prefix(prefix)
            rep.executions += 1
            fp = ex.fingerprint()
            # the preemption count is part of state identity when the
            # budget is bounded: a state first reached expensively must
            # not shadow a cheaper path with budget left to spend
            key = (fp, sleep,
                   preempt if self.max_preemptions is not None else 0)
            if key in visited:
                rep.pruned_dedup += 1
                continue
            visited.add(key)
            rep.states += 1
            states.inc()
            if rep.states > self.max_states:
                rep.truncated += 1
                break
            bad = ex.check_invariants()
            if bad:
                found = Violation(bad[0], bad[1],
                                  " ".join(prefix), len(prefix))
                break
            if len(prefix) >= self.max_depth:
                rep.truncated += 1
                continue
            enabled = ex.enabled()
            if not enabled:
                continue
            # persistent-singleton POR: a transition whose static
            # footprint is disjoint from every OTHER enabled
            # transition's commutes with all of them — exploring it
            # alone covers the state space from here.
            fps = {t: ex.footprint_of(t) for t in enabled}
            chosen = None
            for t in enabled:
                f = fps[t]
                if f is None:
                    continue
                if all(o == t or (fps[o] is not None
                                  and not (f & fps[o]))
                       for o in enabled):
                    chosen = t
                    break
            branch = [chosen] if chosen is not None else enabled
            # sleep sets: skip transitions slept at this state;
            # wake dependents as siblings are taken.
            branch = [t for t in branch if t not in sleep]
            if not branch:
                rep.pruned_sleep += 1
                continue
            taken: list[str] = []
            new_frames = []
            for t in branch:
                if (self.max_preemptions is not None
                        and last is not None
                        and actor_of(t) != last
                        and any(actor_of(e) == last
                                for e in enabled)):
                    if preempt >= self.max_preemptions:
                        rep.truncated += 1
                        continue
                    npre = preempt + 1
                else:
                    npre = preempt
                # sleep set for this child: siblings already taken
                # whose footprints are independent of t stay asleep
                ft = fps[t]
                child_sleep = set()
                for s in sleep | set(taken):
                    fs = fps.get(s, None)
                    if (ft is not None and fs is not None
                            and not (ft & fs)):
                        child_sleep.add(s)
                new_frames.append((prefix + [t],
                                   frozenset(child_sleep),
                                   actor_of(t), npre))
                taken.append(t)
            # DFS order: push reversed so branch[0] explores first
            stack.extend(reversed(new_frames))

        if found is not None:
            found = self.minimize(found)
            reg.counter("modelcheck_violations_total",
                        invariant=found.invariant).inc()
            rep.violation = found
        return rep

    # -- minimization --------------------------------------------------

    def minimize(self, v: Violation) -> Violation:
        """BFS for the SHORTEST violating schedule no longer than the
        found one, then verify it replays byte-for-byte."""
        limit = len(v.schedule.split())
        seen: set[str] = set()
        frontier: list[list[str]] = [[]]
        best = v
        for depth in range(limit + 1):
            nxt: list[list[str]] = []
            for prefix in frontier:
                ex = self._exec_prefix(prefix)
                fp = ex.fingerprint()
                if fp in seen:
                    continue
                seen.add(fp)
                bad = ex.check_invariants()
                if bad:
                    best = Violation(bad[0], bad[1],
                                     " ".join(prefix), len(prefix))
                    # byte-for-byte replay check before trusting it
                    rv = self.replay(best.schedule)
                    if (rv is None
                            or rv.invariant != best.invariant
                            or rv.schedule != best.schedule):
                        raise AssertionError(
                            "minimized schedule failed to replay: "
                            f"{best.schedule!r}")
                    return best
                if depth < limit and len(seen) < self.max_states:
                    for t in ex.enabled():
                        nxt.append(prefix + [t])
            frontier = nxt
            if not frontier:
                break
        return best


def check(model: Model, **kw) -> Report:
    """One-shot convenience: explore ``model`` and return the Report."""
    return Explorer(model, **kw).run()

"""Lock-discipline AST lint (ISSUE 9 tentpole, pass 1 of 3).

Models every lock in the package — ``self._lock = threading.Lock()``
attributes, module-level locks, function-local locks, and the
``racecheck.lock/rlock/condition`` instrumented factories — then walks
each function tracking the set of locks held (``with`` scopes plus
explicit ``acquire()``/``release()`` pairs, including the
acquire-then-``try/finally`` idiom) and reports three rules:

``blocking-call-under-lock``
    A call that can block on the network, a thread, or the clock while
    a lock is held: ``time.sleep``, ``transport.send_msg*`` /
    ``recv_msg*`` / ``connect``, raw socket ``sendall/recv/accept``,
    ``Thread.join``, and ``.wait(...)`` on anything that is NOT the
    held lock itself (``cv.wait`` while holding ``cv`` is the condition
    idiom and allowed; waiting on an Event or a different lock is not).

``lock-order``
    Two locks observed nesting in both orders anywhere in the package
    (an AB/BA inversion against the global acquisition graph), or a
    lock identity re-acquired while already held (the multi-instance
    loop-acquisition pattern — safe only under an explicit ordering
    argument, so it must carry an ``allow``).

``guarded-write``
    A write to ``self.<attr>`` outside any lock when the attribute is
    lock-guarded elsewhere — by explicit ``# guarded-by: <lock>``
    annotation on its ``__init__`` assignment, or inferred when the
    majority (>= 2, and strictly more than unguarded) of its non-init
    writes happen under a lock.  ``__init__`` writes and writes inside
    ``*_locked`` / ``*_holding`` helpers (the repo's caller-holds-it
    naming convention) are exempt.

Everything is intraprocedural by design: cross-function holding is the
runtime detector's job (:mod:`~distkeras_tpu.analysis.racecheck`).
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field

from . import Finding

RULE_BLOCKING = "blocking-call-under-lock"
RULE_ORDER = "lock-order"
RULE_GUARDED = "guarded-write"

# dotted call targets that block (network / clock / disk): the flight
# recorder write+flushes to disk, so calling it under a lock extends
# the critical section by an fsync-class latency — legal only where
# the durability ordering demands it (annotated ``allow`` sites)
_BLOCKING_DOTTED = {
    "time.sleep",
    "transport.connect", "transport.send_msg",
    "transport.send_msg_gather", "transport.recv_msg",
    "transport.recv_msg_into",
    "socket.create_connection",
    "flight_recorder.record", "flight_recorder.flush",
}
# bare names: the repo's module-local sleep shims
_BLOCKING_NAMES = {"_sleep", "sleep"}
# blocking methods regardless of receiver (sockets, file flushes)
_BLOCKING_METHODS = {"sendall", "sendmsg", "recv", "recv_into",
                     "accept", "flush"}
# lock constructors (plain and racecheck-instrumented)
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "racecheck.lock", "racecheck.rlock", "racecheck.condition",
    "Lock", "RLock", "Condition",
}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` source text of a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    return _dotted(value.func) in _LOCK_CTORS


@dataclass
class _Module:
    path: str
    tree: ast.Module
    lines: list[str]
    module_locks: set[str] = field(default_factory=set)
    # (class, attr) -> lock name from a ``# guarded-by:`` annotation
    guarded_by: dict[tuple[str, str], str] = field(default_factory=dict)
    # class -> lock attribute names assigned in that class
    class_locks: dict[str, set[str]] = field(default_factory=dict)


@dataclass
class _Write:
    path: str
    line: int
    cls: str
    attr: str
    func: str
    held: tuple[str, ...]


class _Analysis:
    """Whole-package state: pass 1 collects lock names and annotations,
    pass 2 walks functions against the union of pass-1 knowledge."""

    def __init__(self) -> None:
        self.modules: list[_Module] = []
        self.lock_attr_names: set[str] = set()
        self.findings: list[Finding] = []
        # acquisition graph: (outer, inner) -> first observed site
        self.order_edges: dict[tuple[str, str], tuple[str, int]] = {}
        self.writes: list[_Write] = []

    # -- pass 1 --------------------------------------------------------

    def collect(self, path: str, src: str) -> None:
        tree = ast.parse(src, filename=path)
        mod = _Module(path, tree, src.splitlines())
        for node in tree.body:
            for tgt, value in _assignments(node):
                if isinstance(tgt, ast.Name) and _is_lock_ctor(value):
                    mod.module_locks.add(tgt.id)
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            locks = mod.class_locks.setdefault(cls.name, set())
            for node in ast.walk(cls):
                for tgt, value in _assignments(node):
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        if _is_lock_ctor(value):
                            locks.add(tgt.attr)
                            self.lock_attr_names.add(tgt.attr)
                        line = mod.lines[tgt.lineno - 1]
                        m = _GUARDED_BY_RE.search(line)
                        if m:
                            mod.guarded_by[(cls.name, tgt.attr)] = (
                                m.group(1))
        self.lock_attr_names.update(mod.module_locks)
        self.modules.append(mod)

    # -- pass 2 --------------------------------------------------------

    def analyze(self) -> list[Finding]:
        for mod in self.modules:
            walker = _FuncWalker(self, mod)
            for node in mod.tree.body:
                walker.visit_toplevel(node)
        self._check_order_graph()
        self._check_guarded_writes()
        return self.findings

    def note_edge(self, outer: str, inner: str, path: str, line: int
                  ) -> None:
        if outer == inner:
            self.findings.append(Finding(
                RULE_ORDER, path, line,
                f"{inner} acquired while an instance of {outer} is "
                f"already held (multi-instance nesting needs an "
                f"ordering argument)"))
            return
        self.order_edges.setdefault((outer, inner), (path, line))

    def _check_order_graph(self) -> None:
        adj: dict[str, set[str]] = {}
        for a, b in self.order_edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen, stack = set(), [src]
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(adj.get(n, ()))
            return False

        reported: set[frozenset[str]] = set()
        for (a, b), (path, line) in sorted(self.order_edges.items()):
            if reaches(b, a):
                pair = frozenset((a, b))
                if pair in reported:
                    continue
                reported.add(pair)
                other = self.order_edges.get((b, a))
                where = (f" (reverse order at {other[0]}:{other[1]})"
                         if other else " (via intermediate locks)")
                self.findings.append(Finding(
                    RULE_ORDER, path, line,
                    f"lock-order inversion: {a} -> {b} here but a "
                    f"{b} -> {a} path exists elsewhere{where}"))

    def _check_guarded_writes(self) -> None:
        by_attr: dict[tuple[str, str, str], list[_Write]] = {}
        for w in self.writes:
            by_attr.setdefault((w.path, w.cls, w.attr), []).append(w)
        annotated = {(m.path, cls, attr): lock
                     for m in self.modules
                     for (cls, attr), lock in m.guarded_by.items()}
        for key, writes in sorted(by_attr.items()):
            path, cls, attr = key
            live = [w for w in writes
                    if w.func != "__init__"
                    and not w.func.endswith(("_locked", "_holding"))]
            lock = annotated.get(key)
            if lock is not None:
                for w in live:
                    if not any(h == lock
                               or h.endswith("." + lock)
                               or h.endswith(":" + lock)
                               for h in w.held):
                        self.findings.append(Finding(
                            RULE_GUARDED, w.path, w.line,
                            f"write to {cls}.{attr} outside its "
                            f"declared guard {lock} (guarded-by "
                            f"annotation)"))
                continue
            guarded = [w for w in live if w.held]
            naked = [w for w in live if not w.held]
            if len(guarded) >= 2 and len(guarded) > len(naked):
                majority = guarded[0].held[-1]
                for w in naked:
                    self.findings.append(Finding(
                        RULE_GUARDED, w.path, w.line,
                        f"write to {cls}.{attr} without a lock, but "
                        f"{len(guarded)} other writes hold one "
                        f"(majority guard {majority})"))


def _assignments(node: ast.AST):
    """(target, value) pairs of plain/annotated assignments."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield t, node.value
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        yield node.target, node.value


class _FuncWalker:
    """Per-module linear walk of every function body, tracking held
    locks.  Compound statements recurse with a copy of the held list;
    ``try`` finalizers walk against the live list so the
    acquire-then-``try/finally: release`` idiom balances."""

    def __init__(self, analysis: _Analysis, mod: _Module) -> None:
        self.a = analysis
        self.mod = mod

    def visit_toplevel(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._function(node, cls="")
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    self._function(sub, cls=node.name)

    # -- lock identity -------------------------------------------------

    def _lock_id(self, expr: ast.AST, cls: str,
                 local_locks: set[str]) -> str | None:
        d = _dotted(expr)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            name = parts[0]
            if name in local_locks:
                return f"{self.mod.path}:{name}"
            if name in self.mod.module_locks:
                return f"{self.mod.path}:{name}"
            return None
        if parts[0] == "self" and len(parts) == 2:
            if parts[1] in self.a.lock_attr_names:
                return f"{cls or self.mod.path}.{parts[1]}"
            return None
        # e.g. ``s.lock`` / ``shard.lock``: identify by attribute name
        if parts[-1] in self.a.lock_attr_names:
            return f"*.{parts[-1]}"
        return None

    # -- function walk -------------------------------------------------

    def _function(self, fn, cls: str,
                  outer_locals: frozenset[str] = frozenset()) -> None:
        local_locks = set(outer_locals)
        for node in ast.walk(fn):
            for tgt, value in _assignments(node):
                if isinstance(tgt, ast.Name) and _is_lock_ctor(value):
                    local_locks.add(tgt.id)
                    self.a.lock_attr_names.add(tgt.id)
        ctx = _Ctx(self, cls, fn.name, frozenset(local_locks))
        ctx.walk(fn.body, [])


class _Ctx:
    def __init__(self, walker: _FuncWalker, cls: str, func: str,
                 local_locks: frozenset[str]) -> None:
        self.w = walker
        self.cls = cls
        self.func = func
        self.local_locks = local_locks

    def _lid(self, expr: ast.AST) -> str | None:
        return self.w._lock_id(expr, self.cls, set(self.local_locks))

    def _acquire(self, lid: str, held: list[str], line: int) -> None:
        for h in held:
            self.w.a.note_edge(h, lid, self.w.mod.path, line)
        held.append(lid)

    def walk(self, stmts, held: list[str]) -> None:
        for st in stmts:
            self._statement(st, held)

    def _statement(self, st: ast.stmt, held: list[str]) -> None:
        a = self.w.a
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in st.items:
                lid = self._lid(item.context_expr)
                if lid is not None:
                    self._acquire(lid, inner, st.lineno)
            self.walk(st.body, inner)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # closures run later: analyze with a fresh (empty) held set
            self.w._function(st, cls=self.cls,
                             outer_locals=self.local_locks)
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, ast.Try):
            self.walk(st.body, list(held))
            for h in st.handlers:
                self.walk(h.body, list(held))
            self.walk(st.orelse, list(held))
            # the live list: releases in ``finally`` must balance the
            # acquire that preceded the try statement
            self.walk(st.finalbody, held)
            return
        if isinstance(st, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            self._scan_exprs(self._headers(st), held)
            self.walk(st.body, list(held))
            self.walk(st.orelse, list(held))
            return
        # simple statement: explicit acquire()/release() bookkeeping
        call = (st.value if isinstance(st, ast.Expr)
                and isinstance(st.value, ast.Call) else None)
        if call is not None and isinstance(call.func, ast.Attribute):
            recv_lid = self._lid(call.func.value)
            if recv_lid is not None and call.func.attr == "acquire":
                self._acquire(recv_lid, held, st.lineno)
                return
            if recv_lid is not None and call.func.attr == "release":
                if recv_lid in held:
                    held.remove(recv_lid)
                return
        self._scan_exprs([st], held)
        # track writes to self.<attr> with the current held set
        for tgt, _ in _assignments(st):
            self._note_write(tgt, held, st.lineno)
        if isinstance(st, ast.AugAssign):
            self._note_write(st.target, held, st.lineno)

    def _note_write(self, tgt: ast.AST, held: list[str], line: int
                    ) -> None:
        if (self.cls and isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            self.w.a.writes.append(_Write(
                self.w.mod.path, line, self.cls, tgt.attr,
                self.func, tuple(held)))

    @staticmethod
    def _headers(st: ast.stmt) -> list[ast.AST]:
        if isinstance(st, (ast.For, ast.AsyncFor)):
            return [st.iter]
        if isinstance(st, (ast.If, ast.While)):
            return [st.test]
        return []

    # -- blocking-call scan --------------------------------------------

    def _scan_exprs(self, nodes: list[ast.AST], held: list[str]
                    ) -> None:
        if not held:
            return
        for root in nodes:
            for node in ast.walk(root):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(node, ast.Call):
                    self._check_call(node, held)

    def _check_call(self, call: ast.Call, held: list[str]) -> None:
        a = self.w.a
        d = _dotted(call.func)
        msg = None
        if d in _BLOCKING_DOTTED:
            msg = f"{d}() while holding {held[-1]}"
        elif d in _BLOCKING_NAMES:
            msg = f"{d}() while holding {held[-1]}"
        elif isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            recv = call.func.value
            if meth in _BLOCKING_METHODS:
                msg = (f".{meth}() (blocking I/O) while holding "
                       f"{held[-1]}")
            elif meth == "join" and not self._join_exempt(recv):
                msg = f".join() while holding {held[-1]}"
            elif meth in ("wait", "wait_for"):
                # Condition.wait/wait_for RELEASE the condition they
                # are called on, so waiting on the held lock itself is
                # the intended pattern; waiting on anything else
                # sleeps while keeping our lock
                lid = self._lid(recv)
                if lid is None or lid not in held:
                    what = _dotted(recv) or "<expr>"
                    msg = (f"{what}.{meth}() under {held[-1]} but "
                           f"{what} is not the held lock")
            elif meth == "result":
                # concurrent.futures Future.result() blocks until a
                # worker completes — a worker that needs this lock
                # deadlocks
                msg = (f".result() (blocks on a future) while "
                       f"holding {held[-1]}")
        if msg is not None:
            a.findings.append(Finding(
                RULE_BLOCKING, self.w.mod.path, call.lineno, msg))

    @staticmethod
    def _join_exempt(recv: ast.AST) -> bool:
        """``"".join`` / ``b"".join`` / ``os.path.join`` are not
        thread joins."""
        if isinstance(recv, ast.Constant):
            return isinstance(recv.value, (str, bytes))
        d = _dotted(recv)
        return d is not None and d.split(".")[-1] == "path"


def analyze_paths(repo_root: pathlib.Path,
                  paths: list[pathlib.Path]) -> list[Finding]:
    """Run the lint over ``paths`` (package .py files) with one shared
    lock-name universe and acquisition graph."""
    a = _Analysis()
    rels = [p.relative_to(repo_root).as_posix() for p in paths]
    for rel, p in zip(rels, paths):
        a.collect(rel, p.read_text())
    return sorted(a.analyze(), key=lambda f: (f.path, f.line, f.rule))


def analyze_source(src: str, path: str = "<fixture>") -> list[Finding]:
    """Single-source convenience for tests and seeded fixtures."""
    a = _Analysis()
    a.collect(path, src)
    return sorted(a.analyze(), key=lambda f: (f.path, f.line, f.rule))

"""Abstract model of the replicated-PS protocol for the model checker
(ISSUE 11 tentpole, second half).

Encodes ``parallel/replicated_ps.py``'s election / fencing /
replication protocol as :mod:`modelcheck` actors over a small explicit
world, mirroring the real handlers function-for-function:

==================  =================================================
model function      real counterpart
==================  =================================================
``gate_epoch``      ``PSReplica._gate_epoch_locked`` (+ the demotion
                    half of ``_adopt_epoch_locked``)
``handle_append``   ``PSReplica._append``
``handle_heartbeat````PSReplica._heartbeat``
``handle_bootstrap````PSReplica._bootstrap``
``monitor_tick``    ``PSReplica._monitor_tick`` / ``_run_election``
                    (probe-then-elect with quorum; primaries send
                    heartbeats instead)
``promote``         ``PSReplica.promote`` — the epoch mint IS the real
                    ``mint_epoch``; the winner rule IS the real
                    ``elect`` (both imported, not re-implemented)
``primary_commit``  the worker-commit + sync-``Replicator`` ship path
                    (dedupe check first, per-standby lapse flagging)
==================  =================================================

Log entries are abstracted to ``(epoch_minted, client_seq)`` pairs —
payload bytes don't affect the protocol, and carrying the minting
epoch on each entry lets the prefix-agreement invariant use the Raft
log-matching form.  Message frames keep the real wire shapes: append
``a``/heartbeat ``h``/bootstrap ``b`` requests, ``k``/``f``/``g``
replies, the ``g 0`` bootstrap-me sentinel (``_BOOTSTRAP_ME``), and
the promotion ``base`` stamped on ``a``/``h``.

Deliberate abstractions (documented, not accidental): probes during an
election are atomic world reads (a cut link = timeout = unaccounted, a
crashed host = connection refused = confirmed down); the sync
``ack_timeout`` collapses to the moment a standby crashes or its link
is cut (``_sever``); client retry walks replicas in address order like
``ResilientPSClient``.

Invariants (see ``INVARIANTS``): at-most-one-unfenced-primary-per-
epoch, epoch monotonicity + global mint uniqueness, committed-log-
prefix agreement (log matching), exactly-once application per client
seq, and no-acked-commit-lost while a quorum of replicas holds it.

The mutation harness (``MUTANTS``) flips one real guard at a time —
drop the quorum check, naive ``max+1`` minting (with and without the
equal-epoch fence), skip the divergence/rewind marking, don't
replicate the dedupe table — and the explorer must produce a
counterexample for every one.  Note the documented masking pair:
flipping ONLY the equal-epoch fence is unobservable while residue-
class minting holds (two nodes structurally cannot mint equal epochs),
so the ``equal-epoch`` mutant flips the mint too — defense in depth
means some single flips need their partner removed to show.
"""

from __future__ import annotations

from typing import Optional, Sequence

from distkeras_tpu.analysis.modelcheck import (
    Choose,
    Model,
    Recv,
    Step,
    Timer,
)
from distkeras_tpu.parallel.replicated_ps import (
    _BOOTSTRAP_ME,
    elect,
    mint_epoch,
)

# ---------------------------------------------------------------------
# world


class Node:
    """One replica's protocol-visible state (mirrors ``PSReplica`` +
    its inner PS: epoch, role, fence/diverge flags, promotion base,
    the applied commit log and the commit-seq dedupe table)."""

    def __init__(self, idx: int):
        self.idx = idx
        self.epoch = 0
        self.role = "standby"
        self.crashed = False
        self.fenced = False
        self.diverged = False
        self.base = 0
        self.last_applied = 0
        self.log: list[tuple[int, int]] = []  # (epoch_minted, cseq)
        self.dedupe: set[int] = set()         # commit-seq dedupe table
        self.mints: list[int] = []

    def fingerprint(self):
        return (self.epoch, self.role, self.crashed, self.fenced,
                self.diverged, self.base, self.last_applied,
                tuple(self.log), tuple(sorted(self.dedupe)),
                tuple(self.mints))


class World:
    """Shared state all actors mutate; everything protocol-relevant is
    here (modelcheck discipline: generator locals only drive control
    flow) and enters the fingerprint."""

    def __init__(self, n: int, commits: Sequence[int],
                 net_script: Sequence[tuple] = (),
                 client_cut: Sequence[int] = (),
                 retry_budget: int = 0,
                 mutants: Sequence[str] = ()):
        self.n = int(n)
        self.nodes = [Node(i) for i in range(n)]
        self.cut: set[frozenset] = set()
        self.client_cut = frozenset(int(i) for i in client_cut)
        self.acked: set[int] = set()
        self.holders: dict[int, frozenset] = {}   # cseq -> at ack time
        self.ack_epoch: dict[int, int] = {}       # cseq -> acking epoch
        self.missed: dict[int, set] = {}          # cseq -> lapsed peers
        self.pending: dict[int, dict] = {}        # cseq -> sync wait
        self.minted: list[tuple[int, int]] = []   # (epoch, node)
        self.monotone_violation: Optional[str] = None
        self.commits = list(commits)
        self.net_script = list(net_script)
        self.retry_budget = int(retry_budget)
        self.client = {"i": 0, "p": -1, "retries": int(retry_budget)}
        self.mutants = frozenset(mutants)

    # -- topology ------------------------------------------------------

    def is_cut(self, a: int, b: int) -> bool:
        return frozenset((a, b)) in self.cut

    def quorum(self) -> int:
        return self.n // 2 + 1

    def fingerprint(self):
        return (tuple(nd.fingerprint() for nd in self.nodes),
                tuple(sorted(tuple(sorted(p)) for p in self.cut)),
                tuple(sorted(self.acked)),
                tuple(sorted((k, tuple(sorted(v)))
                             for k, v in self.holders.items())),
                tuple(sorted(self.ack_epoch.items())),
                tuple(sorted((k, tuple(sorted(v)))
                             for k, v in self.missed.items())),
                tuple(sorted(
                    (k, v["p"], v["seq"], tuple(sorted(v["w"])))
                    for k, v in self.pending.items())),
                tuple(self.minted), self.monotone_violation,
                tuple(sorted(self.client.items())))


def _set_epoch(w: World, node: Node, epoch: int) -> None:
    if epoch < node.epoch and w.monotone_violation is None:
        w.monotone_violation = (f"n{node.idx} epoch {node.epoch} -> "
                                f"{epoch}")
    node.epoch = int(epoch)


def _send(ctx, src: int, dst: int, msg: tuple) -> None:
    """Deliver onto the destination's FIFO unless the link is cut or
    the destination is dead (lossy links drop silently, like a socket
    send into a partition)."""
    w = ctx.world
    if w.is_cut(src, dst) or w.nodes[dst].crashed:
        return
    ctx.send(("n", dst), msg)


def _sever(w: World, peer: int) -> None:
    """``ack_timeout`` collapsed: a standby that crashed or got cut
    off stops being waited on — pending sync commits flag it as a
    sync-lapse (``_flag_unreplicated_locked``) and complete."""
    done = []
    for cseq, rec in w.pending.items():
        if peer in rec["w"]:
            rec["w"].discard(peer)
            w.missed.setdefault(cseq, set()).add(peer)
            if not rec["w"]:
                done.append(cseq)
    for cseq in done:
        p = w.pending.pop(cseq)["p"]
        _ack(w, cseq, p)


def _ack(w: World, cseq: int, primary: int) -> None:
    """Server-side commit ack; remember who held the entry AT ack
    time and under which epoch the ack was issued (the durability
    invariant's quorum + epoch conditions).  A retry's re-ack keeps
    the FIRST ack's record — the guarantee attached then."""
    w.acked.add(cseq)
    w.holders.setdefault(cseq, frozenset(
        i for i, nd in enumerate(w.nodes)
        if any(e[1] == cseq for e in nd.log)))
    w.ack_epoch.setdefault(cseq, w.nodes[primary].epoch)


# ---------------------------------------------------------------------
# protocol handlers (mirror replicated_ps.PSReplica)


def gate_epoch(w: World, node: Node, epoch: int,
               base: Optional[int]) -> Optional[tuple]:
    """``_gate_epoch_locked``: fence stale (or equal-epoch-vs-primary)
    writers, adopt newer epochs (demoting + fencing a deposed
    primary), mark ahead standbys diverged via the promotion base."""
    my = node.epoch
    if epoch < my or (epoch == my and node.role == "primary"
                      and "equal-epoch" not in w.mutants):
        return ("f", node.idx, my)
    if epoch > my:
        _set_epoch(w, node, epoch)
        if node.role == "primary":
            node.role = "standby"
            node.fenced = True
            if "skip-rewind" not in w.mutants:
                node.diverged = True
        if (base is not None and node.last_applied > base
                and "skip-rewind" not in w.mutants):
            node.diverged = True
    return None


def handle_append(w: World, i: int, epoch: int, seq: int, base: int,
                  entry: tuple) -> tuple:
    """``_append``: gate, bootstrap-me when diverged, duplicate
    fast-forward, gap reply, or apply (entry + dedupe install)."""
    node = w.nodes[i]
    fence = gate_epoch(w, node, epoch, base)
    if fence is not None:
        return fence
    if node.diverged:
        return ("g", i, _BOOTSTRAP_ME)
    if seq <= node.last_applied:
        return ("k", i, node.last_applied)
    if seq != node.last_applied + 1:
        return ("g", i, node.last_applied + 1)
    node.log.append(tuple(entry))
    if "no-dedupe-repl" not in w.mutants:
        node.dedupe.add(entry[1])
    node.last_applied = seq
    return ("k", i, seq)


def handle_heartbeat(w: World, i: int, epoch: int, head: int,
                     base: int) -> tuple:
    """``_heartbeat``: gate, then report position (gap if behind)."""
    node = w.nodes[i]
    fence = gate_epoch(w, node, epoch, base)
    if fence is not None:
        return fence
    if node.diverged:
        return ("g", i, _BOOTSTRAP_ME)
    if head > node.last_applied:
        return ("g", i, node.last_applied + 1)
    return ("k", i, node.last_applied)


def handle_bootstrap(w: World, i: int, epoch: int, head: int,
                     log: tuple, dedupe: tuple) -> tuple:
    """``_bootstrap``: full-state rewind — replace log, dedupe table
    and position wholesale; clears diverged AND the fence (the node
    rejoins as a clean standby of the new epoch)."""
    node = w.nodes[i]
    fence = gate_epoch(w, node, epoch, None)
    if fence is not None:
        return fence
    node.log = [tuple(e) for e in log]
    node.dedupe = (set() if "no-dedupe-repl" in w.mutants
                   else set(dedupe))
    node.last_applied = int(head)
    node.diverged = False
    node.fenced = False
    return ("k", i, int(head))


def handle_reply(ctx, i: int, msg: tuple) -> None:
    """The primary-side ``Replicator._handle_reply_locked``: ``k``
    completes sync waits, ``f`` means a newer epoch fenced us (adopt +
    demote), ``g`` rewinds the ship cursor (or ships a bootstrap for
    the ``_BOOTSTRAP_ME`` sentinel)."""
    w = ctx.world
    node = w.nodes[i]
    kind, src, val = msg[0], msg[1], msg[2]
    if kind == "k":
        done = []
        for cseq, rec in w.pending.items():
            if rec["p"] == i and src in rec["w"] and rec["seq"] <= val:
                rec["w"].discard(src)
                if not rec["w"]:
                    done.append(cseq)
        for cseq in done:
            p = w.pending.pop(cseq)["p"]
            _ack(w, cseq, p)
        return
    if kind == "f":
        gate_epoch(w, node, val, None)  # adopt + demote if newer
        return
    if kind == "g":
        if node.role != "primary" or node.fenced:
            return
        if val == _BOOTSTRAP_ME or val > len(node.log):
            _send(ctx, i, src,
                  ("b", i, node.epoch, node.last_applied,
                   tuple(node.log), tuple(sorted(node.dedupe))))
        else:
            epoch_minted, cseq = node.log[val - 1]
            _send(ctx, i, src,
                  ("a", i, node.epoch, val, node.base,
                   (epoch_minted, cseq)))


def promote(ctx, i: int, floor: int) -> None:
    """``PSReplica.promote``: mint in this node's residue class (the
    REAL ``mint_epoch``), clear fence/divergence, stamp the promotion
    base, announce to every reachable peer."""
    w = ctx.world
    node = w.nodes[i]
    if node.role == "primary":
        return
    if "naive-mint" in w.mutants or "equal-epoch" in w.mutants:
        new_epoch = max(node.epoch, floor) + 1
    else:
        new_epoch = mint_epoch(node.epoch, floor, i, w.n)
    _set_epoch(w, node, new_epoch)
    node.mints.append(new_epoch)
    w.minted.append((new_epoch, i))
    node.role = "primary"
    node.fenced = False
    node.diverged = False
    node.base = node.last_applied
    for j in range(w.n):
        if j != i:
            _send(ctx, i, j, ("h", i, new_epoch, node.last_applied,
                              node.base))


def monitor_tick(ctx, i: int) -> None:
    """``_monitor_tick``: a primary heartbeats its peers; a standby
    that went quiet runs ``_run_election`` — probe EVERY peer (cut
    link = timeout = unaccounted; crashed host = connection refused =
    accounted), stand down without quorum or if the primary answered,
    else promote the ``elect`` winner with the observed epoch floor."""
    w = ctx.world
    node = w.nodes[i]
    if node.crashed:
        return
    if node.role == "primary":
        if not node.fenced:
            for j in range(w.n):
                if j != i:
                    _send(ctx, i, j, ("h", i, node.epoch,
                                      node.last_applied, node.base))
        return
    cands = [(node.epoch, node.last_applied, i)]
    accounted = 1  # self
    primary_alive = False
    for j in range(w.n):
        if j == i:
            continue
        peer = w.nodes[j]
        if w.is_cut(i, j):
            continue  # probe timeout: unaccounted
        if peer.crashed:
            accounted += 1  # connection refused: confirmed down
            continue
        accounted += 1
        if peer.role == "primary" and peer.epoch >= node.epoch:
            primary_alive = True
        cands.append((peer.epoch, peer.last_applied, j))
    if primary_alive:
        return
    if ("no-quorum" not in w.mutants
            and 2 * accounted <= w.n):
        return
    if elect(cands) == i:
        promote(ctx, i, floor=max(c[0] for c in cands))


def primary_commit(ctx, p: int, cseq: int) -> None:
    """One worker commit at the primary: dedupe-table check first
    (exactly-once across retries), then apply + sync-ship to every
    reachable standby, flagging unreachable ones as sync lapses."""
    w = ctx.world
    node = w.nodes[p]
    if cseq in node.dedupe:
        _ack(w, cseq, p)  # retried commit: already applied once
        return
    seq = node.last_applied + 1
    entry = (node.epoch, cseq)
    node.log.append(entry)
    node.dedupe.add(cseq)
    node.last_applied = seq
    waiting = set()
    for j in range(w.n):
        if j == p:
            continue
        if w.nodes[j].crashed or w.is_cut(p, j):
            w.missed.setdefault(cseq, set()).add(j)
            continue
        _send(ctx, p, j, ("a", p, node.epoch, seq, node.base, entry))
        waiting.add(j)
    if waiting:
        w.pending[cseq] = {"p": p, "seq": seq, "w": waiting}
    else:
        _ack(w, cseq, p)  # total sync lapse: acked-but-flagged


# ---------------------------------------------------------------------
# actors


def node_net(i: int):
    """The replication-wire servicing loop of node ``i`` (the accept
    thread + ``Replicator`` reply path of the real replica)."""

    def actor(ctx):
        w = ctx.world
        while True:
            msg = yield Recv(("n", i))
            if w.nodes[i].crashed:
                continue  # dead letter
            kind, src = msg[0], msg[1]
            if kind == "a":
                reply = handle_append(w, i, msg[2], msg[3], msg[4],
                                      msg[5])
                _send(ctx, i, src, reply)
            elif kind == "h":
                reply = handle_heartbeat(w, i, msg[2], msg[3],
                                         msg[4])
                _send(ctx, i, src, reply)
            elif kind == "b":
                reply = handle_bootstrap(w, i, msg[2], msg[3],
                                         msg[4], msg[5])
                _send(ctx, i, src, reply)
            else:  # k / f / g
                handle_reply(ctx, i, msg)
    return actor


def node_timer(i: int):
    """Node ``i``'s monitor loop: each Timer fire is one
    ``_monitor_tick`` (heartbeat when primary, election when a quiet
    standby — the model's Timer IS the failover timeout expiring)."""

    def actor(ctx):
        w = ctx.world
        while True:
            yield Timer("tick")
            if w.nodes[i].crashed:
                return
            monitor_tick(ctx, i)
    return actor


def client_actor(ctx):
    """``ResilientPSClient``: walk replicas in address order for an
    unfenced primary, commit, await the sync ack, retry across
    failover on a lost ack or a dead primary (dedupe makes the retry
    exactly-once)."""
    w = ctx.world
    st = w.client
    yield Step("start")
    while st["i"] < len(w.commits):
        cseq = w.commits[st["i"]]
        p = next((j for j, nd in enumerate(w.nodes)
                  if nd.role == "primary" and not nd.fenced
                  and not nd.crashed and j not in w.client_cut),
                 None)
        if p is None:
            yield Step("wait-primary")
            continue
        primary_commit(ctx, p, cseq)
        st["p"] = p
        while cseq not in w.acked:
            nd = w.nodes[st["p"]]
            if nd.crashed or nd.fenced or nd.role != "primary":
                break  # connection died mid-commit
            yield Step("wait-ack")
        if cseq in w.acked and st["retries"] > 0:
            wire = yield Choose("ackwire", ["ok", "lost"])
            if wire == "lost":
                st["retries"] -= 1
                continue  # retry the SAME cseq (dedupe's job)
        elif cseq not in w.acked:
            if st["retries"] > 0:
                st["retries"] -= 1
                continue
        st["i"] += 1
        st["retries"] = w.retry_budget


def net_actor(ctx):
    """Scripted fault injection: each step cuts or heals one link at a
    scheduler-chosen moment (the WHEN is the explored nondeterminism;
    the WHAT is the scenario script)."""
    w = ctx.world
    for act, a, b in w.net_script:
        yield Step(f"{act}:{a}-{b}")
        pair = frozenset((a, b))
        if act == "cut":
            w.cut.add(pair)
            _sever(w, a)
            _sever(w, b)
        else:
            w.cut.discard(pair)


def make_crash(i: int):
    """Explorer-level kill of node ``i``: mark it dead, drop its
    inbox, and complete (as lapses) any sync waits on it; a crashed
    PRIMARY's pending commits simply never ack (the client's retry
    path owns them)."""

    def on_crash(ctx):
        w = ctx.world
        w.nodes[i].crashed = True
        ctx.drain(("n", i))
        for cseq in [c for c, rec in w.pending.items()
                     if rec["p"] == i]:
            del w.pending[cseq]
        _sever(w, i)
    return on_crash


# ---------------------------------------------------------------------
# invariants


def inv_one_primary(w: World) -> Optional[str]:
    by_epoch: dict[int, list] = {}
    for i, nd in enumerate(w.nodes):
        if nd.role == "primary" and not nd.fenced and not nd.crashed:
            by_epoch.setdefault(nd.epoch, []).append(i)
    for epoch, idxs in by_epoch.items():
        if len(idxs) > 1:
            return (f"nodes {idxs} are both unfenced primaries of "
                    f"epoch {epoch}")
    return None


def inv_epoch_unique(w: World) -> Optional[str]:
    if w.monotone_violation:
        return f"epoch moved backwards: {w.monotone_violation}"
    epochs = [e for e, _ in w.minted]
    if len(set(epochs)) != len(epochs):
        return f"epoch minted twice: {sorted(w.minted)}"
    for nd in w.nodes:
        if any(b <= a for a, b in zip(nd.mints, nd.mints[1:])):
            return f"n{nd.idx} mints not increasing: {nd.mints}"
    return None


def inv_prefix_agreement(w: World) -> Optional[str]:
    """Raft log matching: if two logs hold an entry with the same
    (position, minting epoch), everything before it is identical —
    the form that tolerates a stale primary's not-yet-rewound tail
    (different epochs at the same position constrain nothing)."""
    for a in range(w.n):
        for b in range(a + 1, w.n):
            la, lb = w.nodes[a].log, w.nodes[b].log
            for k in range(min(len(la), len(lb)) - 1, -1, -1):
                if la[k][0] == lb[k][0]:
                    if la[:k + 1] != lb[:k + 1]:
                        return (f"n{a}/n{b} share epoch at seq "
                                f"{k + 1} but prefixes differ: "
                                f"{la[:k + 1]} vs {lb[:k + 1]}")
                    break
    return None


def inv_exactly_once(w: World) -> Optional[str]:
    for nd in w.nodes:
        seen = [e[1] for e in nd.log]
        if len(set(seen)) != len(seen):
            return (f"n{nd.idx} applied a commit twice: log "
                    f"{nd.log}")
    return None


def inv_durability(w: World) -> Optional[str]:
    """No acked commit that a QUORUM held at ack time may be missing
    from any unfenced primary AT OR ABOVE the acking epoch.  Two
    documented exemptions: sub-quorum acks are the sync-lapse
    degradation, and a stale LOWER-epoch primary is the tolerated
    split-brain transient — it gets fenced on first contact, and a
    quorum election can never seat a >=-epoch primary without the
    commit (the winner maximizes ``last_applied`` over a majority
    that intersects the holders)."""
    q = w.quorum()
    primaries = [nd for nd in w.nodes
                 if nd.role == "primary" and not nd.fenced
                 and not nd.crashed]
    for cseq in w.acked:
        if len(w.holders.get(cseq, frozenset())) < q:
            continue
        for nd in primaries:
            if nd.epoch < w.ack_epoch.get(cseq, 0):
                continue
            if all(e[1] != cseq for e in nd.log):
                return (f"acked commit {cseq} (quorum-held at ack) "
                        f"is missing from primary n{nd.idx} "
                        f"epoch {nd.epoch}")
    return None


INVARIANTS = [
    ("one-primary-per-epoch", inv_one_primary),
    ("epoch-unique-monotone", inv_epoch_unique),
    ("prefix-agreement", inv_prefix_agreement),
    ("exactly-once", inv_exactly_once),
    ("durable-acked-commits", inv_durability),
]


# ---------------------------------------------------------------------
# scenarios


def _base_world(n: int, **kw) -> World:
    """n0 is the bootstrapped primary (its mint recorded), peers are
    caught-up standbys of its epoch — the post-``make_replica_group``
    steady state every scenario starts from."""
    w = World(n, **kw)
    e0 = mint_epoch(0, 0, 0, n)
    n0 = w.nodes[0]
    _set_epoch(w, n0, e0)
    n0.role = "primary"
    n0.mints.append(e0)
    w.minted.append((e0, 0))
    for nd in w.nodes[1:]:
        _set_epoch(w, nd, e0)
    return w


def _seed_commit(w: World, cseq: int,
                 holders: Sequence[int]) -> None:
    """Pre-apply an acked commit on ``holders`` (scenario setup:
    shrinks the schedule prefix the explorer must wade through)."""
    for i in holders:
        nd = w.nodes[i]
        nd.log.append((w.nodes[0].epoch, cseq))
        nd.dedupe.add(cseq)
        nd.last_applied += 1
    w.acked.add(cseq)
    w.holders[cseq] = frozenset(holders)
    w.ack_epoch[cseq] = w.nodes[0].epoch
    missing = set(range(w.n)) - set(holders)
    if missing:
        w.missed[cseq] = missing


def _assemble(make_world, *, crashable=(), timers=(0, 1, 2),
              timer_budget=2, crash_budget=1) -> Model:
    probe = make_world()
    n = probe.n
    m = Model(make_world)
    for i in range(n):
        m.actor(f"n{i}", node_net(i))
    for i in timers:
        m.actor(f"n{i}.t", node_timer(i))
    if probe.commits:
        m.actor("client", client_actor)
    if probe.net_script:
        m.actor("net", net_actor)
    for i in crashable:
        m.allow_crash(f"n{i}", make_crash(i), budget=crash_budget)
    m.timer_budget = int(timer_budget)
    for name, fn in INVARIANTS:
        m.invariant(name, fn)
    return m


def scenario_failover(mutants=()) -> tuple[Model, dict]:
    """Primary crash + quorum re-election + client retry across the
    boundary: the exactly-once / dedupe-replication story."""
    muts = tuple(mutants)

    def make_world():
        w = _base_world(3, commits=[1], retry_budget=1,
                        mutants=muts)
        return w
    model = _assemble(make_world, crashable=(0,), timers=(1, 2),
                      timer_budget=2)
    return model, {"max_depth": 18, "max_states": 150_000}


def scenario_partition(mutants=()) -> tuple[Model, dict]:
    """A standby isolated by a partition while commits flow on the
    majority side: the quorum story (the minority must stand down)."""
    muts = tuple(mutants)

    def make_world():
        w = _base_world(3, commits=[2],
                        net_script=[("cut", 0, 2), ("cut", 1, 2)],
                        client_cut=(2,), mutants=muts)
        _seed_commit(w, 1, (0, 1, 2))
        return w
    model = _assemble(make_world, crashable=(), timers=(2,),
                      timer_budget=2)
    return model, {"max_depth": 14, "max_states": 150_000}


def scenario_split(mutants=()) -> tuple[Model, dict]:
    """Primary dead AND the two standbys partitioned from each other:
    concurrent elections on both sides — the residue-class epoch
    uniqueness story."""
    muts = tuple(mutants)

    def make_world():
        w = _base_world(3, commits=[],
                        net_script=[("cut", 1, 2)], mutants=muts)
        _seed_commit(w, 1, (0, 1, 2))
        return w
    model = _assemble(make_world, crashable=(0,), timers=(1, 2),
                      timer_budget=2)
    return model, {"max_depth": 12, "max_states": 150_000}


def scenario_rewind(mutants=()) -> tuple[Model, dict]:
    """An isolated old primary with an unreplicated tail vs a freshly
    elected majority primary, links healing mid-stream: the
    divergence / bootstrap-rewind story.  Starts mid-partition with
    the lapsed tail already applied (seeded) so the explorer spends
    its depth on the interesting part."""
    muts = tuple(mutants)

    def make_world():
        w = _base_world(3, commits=[3, 4],
                        net_script=[("heal", 0, 1), ("heal", 0, 2)],
                        client_cut=(0,), mutants=muts)
        _seed_commit(w, 1, (0, 1, 2))
        # the old primary's isolated, sync-lapsed tail
        _seed_commit(w, 2, (0,))
        w.cut.add(frozenset((0, 1)))
        w.cut.add(frozenset((0, 2)))
        return w
    model = _assemble(make_world, crashable=(), timers=(1,),
                      timer_budget=3)
    return model, {"max_depth": 22, "max_states": 400_000}


SCENARIOS = {
    "failover": scenario_failover,
    "partition": scenario_partition,
    "split": scenario_split,
    "rewind": scenario_rewind,
}

#: mutant -> (guard it flips, scenario that exposes it, invariant
#: expected to break).  Every entry must yield a counterexample.
MUTANTS = {
    "no-quorum": ("election promotes without a majority accounted",
                  "partition", "durable-acked-commits"),
    "naive-mint": ("max+1 epoch mint instead of residue classes",
                   "split", "one-primary-per-epoch"),
    "equal-epoch": ("naive mint AND equal-epoch frames accepted "
                    "(the fence alone is masked by residue minting)",
                    "split", "one-primary-per-epoch"),
    "skip-rewind": ("ahead standby acks a new primary's seqs as "
                    "duplicates instead of demanding a resync",
                    "rewind", "prefix-agreement"),
    "no-dedupe-repl": ("replication installs entries but not the "
                       "commit-seq dedupe table",
                       "failover", "exactly-once"),
}


def build(scenario: str, mutants: Sequence[str] = ()
          ) -> tuple[Model, dict]:
    """Scenario name (+ optional mutant set) -> (Model, explorer
    bounds)."""
    unknown = set(mutants) - set(MUTANTS)
    if unknown:
        raise KeyError(f"unknown mutants: {sorted(unknown)}")
    return SCENARIOS[scenario](mutants=tuple(mutants))

"""Concurrency & protocol static-analysis suite (ISSUE 9).

Three passes over the package, run together by
``scripts/lint_static.py`` and proven on seeded violations by
``tests/test_static_analysis.py``:

- :mod:`~distkeras_tpu.analysis.lockcheck` — AST lock-discipline lint:
  blocking calls under a held lock, lock-order inversions, and writes
  escaping the lock that guards an attribute elsewhere.
- :mod:`~distkeras_tpu.analysis.racecheck` — opt-in RUNTIME detector:
  Eraser-style lockset race detection plus wait-for-graph deadlock
  detection, with a disabled-by-default no-op fast path (the factories
  hand back plain ``threading`` primitives when off).
- :mod:`~distkeras_tpu.analysis.surfaces` — surface-drift lint: every
  telemetry metric/span name, flight-recorder kind, SLO signal, history
  key, and wire opcode is AST-extracted and cross-checked against
  ``docs/API.md`` and ``transport.WIRE_OPS``.

A fourth pass (ISSUE 11) turns the suite inward:

- :mod:`~distkeras_tpu.analysis.modelcheck` +
  :mod:`~distkeras_tpu.analysis.protomodel` — a CHESS/DPOR-style
  protocol model checker: exhaustive bounded exploration of the
  replicated-PS election/fencing/replication interleavings with
  invariant checks on every state and minimized, replayable
  counterexamples (``scripts/check_protocol.py``).

Findings are suppressed in place with ``# lint: allow(<rule>)`` (plus a
justification) on the flagged or preceding line, or — for triaged
intentionals that span refactors — via the committed baseline file
``scripts/lint_baseline.txt`` (one ``rule|path|message`` key per line).
Suppressions themselves are linted: ``dead_suppressions`` flags
baseline entries and allow comments no raw finding matches anymore
(the ``dead-suppression`` rule), so the baseline cannot silently rot.
"""

from __future__ import annotations

import pathlib
import re
from dataclasses import dataclass

#: package subtrees the AST passes walk (tests/scripts lint themselves)
PACKAGE = "distkeras_tpu"


@dataclass(frozen=True)
class Finding:
    """One lint finding, printable as ``path:line: [rule] message``."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def baseline_key(self) -> str:
        """Line-number-free identity used by the committed baseline, so
        unrelated edits shifting a file do not churn the baseline."""
        return f"{self.rule}|{self.path}|{self.message}"


_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")


def allowed_rules(lines: list[str], lineno: int) -> set[str]:
    """Rules suppressed at 1-based ``lineno``: an ``# lint: allow(...)``
    comment on the flagged line or anywhere in the contiguous comment
    block directly above it (justifications usually wrap)."""
    out: set[str] = set()

    def scan(ln: int) -> None:
        for m in _ALLOW_RE.finditer(lines[ln]):
            out.update(r.strip() for r in m.group(1).split(","))

    if 0 <= lineno - 1 < len(lines):
        scan(lineno - 1)
    ln = lineno - 2
    while 0 <= ln < len(lines) and lines[ln].lstrip().startswith("#"):
        scan(ln)
        ln -= 1
    return out


def filter_suppressed(findings: list[Finding],
                      sources: dict[str, list[str]]
                      ) -> tuple[list[Finding], int]:
    """Drop findings carrying an in-source ``allow`` for their rule.
    ``sources`` maps repo-relative path -> source lines."""
    kept, dropped = [], 0
    for f in findings:
        lines = sources.get(f.path)
        if lines is not None and f.rule in allowed_rules(lines, f.line):
            dropped += 1
        else:
            kept.append(f)
    return kept, dropped


def load_baseline(path: pathlib.Path) -> set[str]:
    """Baseline keys (``Finding.baseline_key`` lines; ``#`` comments and
    blanks ignored).  A missing file is an empty baseline."""
    if not path.exists():
        return set()
    out = set()
    for line in path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            out.add(line)
    return out


RULE_DEAD = "dead-suppression"


def dead_suppressions(raw_findings: list[Finding],
                      sources: dict[str, list[str]],
                      baseline: set[str]) -> list[Finding]:
    """Suppressions that no longer suppress anything: baseline keys no
    RAW (pre-suppression) finding produces, and ``allow(rule)``
    comments whose covered line has no raw finding of that rule.
    Both start as honest triage and rot into a blind spot when the
    flagged code is fixed or moves — these findings make the rot
    visible (``lint_static.py`` reports them; ``--strict-baseline``
    fails on them)."""
    out: list[Finding] = []

    live_keys = {f.baseline_key() for f in raw_findings}
    for key in sorted(baseline - live_keys):
        path = key.split("|", 2)[1] if key.count("|") >= 2 else "?"
        out.append(Finding(
            RULE_DEAD, path, 0,
            f"baseline entry matches no finding: {key}"))

    by_site: dict[tuple[str, int], set[str]] = {}
    for f in raw_findings:
        by_site.setdefault((f.path, f.line), set()).add(f.rule)
    for path, lines in sorted(sources.items()):
        for idx, text in enumerate(lines):
            m = _ALLOW_RE.search(text)
            if not m:
                continue
            lineno = idx + 1
            # a comment-only line covers the first code line below
            # the contiguous comment block (mirrors allowed_rules'
            # upward scan)
            if text.lstrip().startswith("#"):
                covered = idx + 1
                while (covered < len(lines)
                       and lines[covered].lstrip().startswith("#")):
                    covered += 1
                covered += 1  # 1-based
            else:
                covered = lineno
            found = by_site.get((path, covered), set())
            for rule in (r.strip() for r in m.group(1).split(",")):
                # only well-formed rule names: docstrings discussing
                # the ``allow(<rule>)`` syntax are not suppressions
                if not re.fullmatch(r"[a-z][a-z0-9-]*", rule):
                    continue
                if rule not in found:
                    out.append(Finding(
                        RULE_DEAD, path, lineno,
                        f"allow({rule}) suppresses nothing (no "
                        f"{rule} finding at line {covered})"))
    return out


def package_files(repo_root: pathlib.Path) -> list[pathlib.Path]:
    """All package source files, sorted for deterministic reports."""
    root = repo_root / PACKAGE
    return sorted(p for p in root.rglob("*.py")
                  if "__pycache__" not in p.parts)


def read_sources(repo_root: pathlib.Path,
                 paths: list[pathlib.Path]) -> dict[str, list[str]]:
    return {p.relative_to(repo_root).as_posix():
            p.read_text().splitlines() for p in paths}

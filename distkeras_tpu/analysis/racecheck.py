"""Runtime lockset race + deadlock detector (ISSUE 9 tentpole, pass 2).

Opt-in, disabled by default, with the same no-op discipline as
telemetry: the lock factories (:func:`lock`, :func:`rlock`,
:func:`condition`) hand back PLAIN ``threading`` primitives while the
detector is off, so the disabled path costs exactly one module-global
bool test at construction time and nothing at all per acquire.  Every
lock-bearing runtime module constructs its locks through these
factories; enabling the detector before constructing a PS / gateway /
engine therefore instruments that object's whole locking surface.

When enabled:

- ``CheckedLock`` / ``CheckedRLock`` maintain a per-thread held set and
  a global instance-level acquisition-order graph.  An AB/BA cycle in
  the order graph records a ``lock-order-cycle`` report the moment the
  second order is observed — no unlucky interleaving required.  A
  blocking acquire additionally walks the wait-for graph (thread ->
  wanted lock -> owning thread) and raises :class:`DeadlockError`
  instead of deadlocking; a same-thread re-acquire of a non-reentrant
  lock raises immediately (that IS a deadlock, deterministically).
- :class:`Guarded` wraps an object and feeds every attribute / item
  access through the Eraser lockset algorithm (Savage et al. 1997):
  each shared location keeps a candidate lockset, refined by
  intersection with the locks held at each access; a write-shared
  location whose lockset goes empty is a data race, reported with the
  stacks of BOTH conflicting accesses.  Passing an explicit ``lock``
  also enforces the simple discipline "never touch without it".

``enable()`` inside the chaos / gateway / sharded-PS suites keeps those
tests honest: they fail on any report, so a new nesting or unguarded
access breaks CI rather than production.
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field


class DeadlockError(RuntimeError):
    """A blocking acquire would complete a wait-for cycle."""


@dataclass(frozen=True)
class Report:
    kind: str  # "lock-order-cycle" | "deadlock" | "race" | "unguarded"
    detail: str
    stacks: tuple[str, ...] = ()

    def __str__(self) -> str:
        return f"[{self.kind}] {self.detail}"


@dataclass
class _VarState:
    """Eraser per-location state machine."""
    owner: int
    written: bool
    state: str = "exclusive"  # exclusive | shared | shared-modified
    lockset: frozenset[int] | None = None
    last: tuple[int, bool, str] = (0, False, "")
    reported: bool = False


@dataclass
class _Detector:
    raise_on_deadlock: bool = True
    mutex: threading.Lock = field(default_factory=threading.Lock)
    reports: list[Report] = field(default_factory=list)
    # instance-level acquisition order graph: id(outer) -> {id(inner)}
    order: dict[int, set[int]] = field(default_factory=dict)
    names: dict[int, str] = field(default_factory=dict)
    edge_sites: dict[tuple[int, int], str] = field(default_factory=dict)
    owners: dict[int, int] = field(default_factory=dict)  # lock->tid
    wanted: dict[int, object] = field(default_factory=dict)  # tid->lock
    vars: dict[object, _VarState] = field(default_factory=dict)


_enabled = False
_det = _Detector()
_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _depths() -> dict[int, int]:
    d = getattr(_tls, "depths", None)
    if d is None:
        d = _tls.depths = {}
    return d


def _stack(skip: int = 3) -> str:
    return "".join(traceback.format_stack()[:-skip])


def enable(raise_on_deadlock: bool = True) -> None:
    """Turn the detector on and reset all prior state.  Locks built by
    the factories AFTER this point are instrumented."""
    global _enabled, _det
    _det = _Detector(raise_on_deadlock=raise_on_deadlock)
    _enabled = True


def disable() -> list[Report]:
    """Turn the detector off and return the accumulated reports.
    Instrumented locks already in the wild degrade to a single bool
    test per acquire."""
    global _enabled
    _enabled = False
    return list(_det.reports)


def enabled() -> bool:
    return _enabled


def reports() -> list[Report]:
    return list(_det.reports)


def held_locks() -> tuple[str, ...]:
    """Names of the instrumented locks this thread currently holds."""
    return tuple(lk.name for lk in _held())


# -- lock factories (the no-op fast path) ------------------------------


def lock(name: str = "lock"):
    """A mutex: plain ``threading.Lock`` when the detector is off,
    :class:`CheckedLock` when on."""
    return CheckedLock(name) if _enabled else threading.Lock()


def rlock(name: str = "rlock"):
    return CheckedRLock(name) if _enabled else threading.RLock()


def condition(name: str = "cond"):
    """A condition over a (possibly instrumented) RLock — the gateway's
    ``Condition(RLock())`` idiom."""
    return threading.Condition(rlock(name))


# -- instrumented locks ------------------------------------------------


class _CheckedBase:
    def __init__(self, name: str, inner) -> None:
        self.name = name
        self._inner = inner

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"

    # order-graph bookkeeping, called before the inner acquire
    def _pre_acquire(self) -> None:
        me = id(self)
        held = _held()
        with _det.mutex:
            _det.names[me] = self.name
            for h in held:
                o = id(h)
                if me in _det.order.setdefault(o, set()):
                    continue
                _det.order[o].add(me)
                _det.edge_sites[(o, me)] = _stack()
                self._cycle_check(o, me, h)

    def _cycle_check(self, outer: int, inner: int, outer_lock) -> None:
        # does inner already reach outer?  (caller holds _det.mutex)
        seen, stack = set(), [inner]
        while stack:
            n = stack.pop()
            if n == outer:
                rev = _det.edge_sites.get((inner, outer), "")
                _det.reports.append(Report(
                    "lock-order-cycle",
                    f"{_det.names.get(outer, '?')} -> "
                    f"{self.name} nests here, but the reverse order "
                    f"was also observed",
                    (_stack(), rev)))
                return
            if n in seen:
                continue
            seen.add(n)
            stack.extend(_det.order.get(n, ()))

    def _blocking_acquire(self, timeout: float) -> bool:
        """Acquire with wait-for-graph deadlock detection: poll the
        inner lock and re-check the cycle each interval, so the check
        fires no matter which thread registered its intent last."""
        me = threading.get_ident()
        with _det.mutex:
            _det.wanted[me] = self
        try:
            import time
            deadline = (None if timeout is None or timeout < 0
                        else time.monotonic() + timeout)
            while True:
                step = 0.05
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                    step = min(step, left)
                if self._inner.acquire(True, step):
                    return True
                self._waitfor_check(me)
        finally:
            with _det.mutex:
                _det.wanted.pop(me, None)

    def _waitfor_check(self, me: int) -> None:
        with _det.mutex:
            seen = {me}
            lk = self
            while True:
                owner = _det.owners.get(id(lk))
                if owner is None:
                    return
                if owner in seen:
                    rep = Report(
                        "deadlock",
                        f"wait-for cycle: thread {me} wants "
                        f"{lk.name!r} held by thread {owner} which is "
                        f"itself blocked", (_stack(),))
                    _det.reports.append(rep)
                    raise DeadlockError(str(rep))
                seen.add(owner)
                lk = _det.wanted.get(owner)
                if lk is None:
                    return

    def _got(self) -> None:
        _held().append(self)
        with _det.mutex:
            _det.owners[id(self)] = threading.get_ident()

    def _dropped(self) -> None:
        held = _held()
        if self in held:
            held.remove(self)
        with _det.mutex:
            _det.owners.pop(id(self), None)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()


class CheckedLock(_CheckedBase):
    def __init__(self, name: str = "lock") -> None:
        super().__init__(name, threading.Lock())

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not _enabled:
            return self._inner.acquire(blocking, timeout)
        if self in _held():
            rep = Report(
                "deadlock",
                f"thread re-acquiring non-reentrant lock "
                f"{self.name!r} it already holds", (_stack(),))
            _det.reports.append(rep)
            raise DeadlockError(str(rep))
        self._pre_acquire()
        if self._inner.acquire(False):
            self._got()
            return True
        if not blocking:
            return False
        if self._blocking_acquire(timeout):
            self._got()
            return True
        return False

    def release(self) -> None:
        if _enabled:
            self._dropped()
        self._inner.release()


class CheckedRLock(_CheckedBase):
    """Reentrant variant.  Exposes ``_is_owned`` / ``_release_save`` /
    ``_acquire_restore`` so ``threading.Condition`` treats it exactly
    like a native RLock (``wait()`` fully releases and the held set
    tracks that)."""

    def __init__(self, name: str = "rlock") -> None:
        super().__init__(name, threading.RLock())

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not _enabled:
            return self._inner.acquire(blocking, timeout)
        depths = _depths()
        if depths.get(id(self), 0) > 0:  # recursion: no bookkeeping
            got = self._inner.acquire(blocking, timeout)
            if got:
                depths[id(self)] += 1
            return got
        self._pre_acquire()
        if self._inner.acquire(False):
            self._got()
            depths[id(self)] = 1
            return True
        if not blocking:
            return False
        if self._blocking_acquire(timeout):
            self._got()
            depths[id(self)] = 1
            return True
        return False

    def release(self) -> None:
        if _enabled:
            depths = _depths()
            n = depths.get(id(self), 0)
            if n <= 1:
                depths.pop(id(self), None)
                self._dropped()
            else:
                depths[id(self)] = n - 1
        self._inner.release()

    # Condition protocol ----------------------------------------------

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        depths = _depths()
        n = depths.pop(id(self), 0)
        self._dropped()
        return self._inner._release_save(), n

    def _acquire_restore(self, saved):
        inner_state, n = saved
        self._inner._acquire_restore(inner_state)
        self._got()
        if n:
            _depths()[id(self)] = n


# -- Eraser lockset algorithm ------------------------------------------


def record_access(key, write: bool) -> None:
    """Feed one access to shared location ``key`` through the lockset
    state machine.  No-op while disabled."""
    if not _enabled:
        return
    me = threading.get_ident()
    held = frozenset(id(lk) for lk in _held())
    lock_names = tuple(lk.name for lk in _held())
    with _det.mutex:
        v = _det.vars.get(key)
        if v is None:
            _det.vars[key] = _VarState(
                owner=me, written=write,
                last=(me, write, _stack()))
            return
        if v.state == "exclusive" and v.owner == me:
            v.written = v.written or write
            v.last = (me, write, _stack())
            return
        if v.state == "exclusive":  # second thread arrives
            v.state = ("shared-modified" if (write or v.written)
                       else "shared")
            v.lockset = held
        else:
            v.lockset = (v.lockset or frozenset()) & held
            if write:
                v.state = "shared-modified"
        racy = (v.state == "shared-modified" and not v.lockset
                and not v.reported)
        prev = v.last
        v.last = (me, write, _stack())
        if racy:
            v.reported = True
            _det.reports.append(Report(
                "race",
                f"{key!r}: {'write' if write else 'read'} by thread "
                f"{me} holding {lock_names or '()'} conflicts with "
                f"{'write' if prev[1] else 'read'} by thread "
                f"{prev[0]} — candidate lockset is empty",
                (prev[2], v.last[2])))


class Guarded:
    """Access recorder: wrap a shared object so every attribute / item
    access feeds the lockset algorithm.  With an explicit ``lock``, an
    access made while NOT holding it is reported immediately
    (``unguarded``) in addition to the Eraser refinement."""

    __slots__ = ("_rc_obj", "_rc_lock", "_rc_name")

    def __init__(self, obj, lock=None, name: str | None = None):
        object.__setattr__(self, "_rc_obj", obj)
        object.__setattr__(self, "_rc_lock", lock)
        object.__setattr__(self, "_rc_name",
                           name or type(obj).__name__)

    def _rc_check(self, field: str, write: bool) -> None:
        if not _enabled:
            return
        lk = self._rc_lock
        if lk is not None and lk not in _held():
            _det.reports.append(Report(
                "unguarded",
                f"{self._rc_name}.{field} "
                f"{'written' if write else 'read'} without holding "
                f"{getattr(lk, 'name', lk)!r}", (_stack(),)))
        record_access((self._rc_name, field), write)

    def __getattr__(self, attr):
        self._rc_check(attr, write=False)
        return getattr(self._rc_obj, attr)

    def __setattr__(self, attr, value):
        self._rc_check(attr, write=True)
        setattr(self._rc_obj, attr, value)

    def __getitem__(self, k):
        self._rc_check(f"[{k!r}]", write=False)
        return self._rc_obj[k]

    def __setitem__(self, k, v):
        self._rc_check(f"[{k!r}]", write=True)
        self._rc_obj[k] = v

    def __delitem__(self, k):
        # dunders bypass __getattr__ (special-method lookup goes to
        # the type), so deletion needs its own interception or it
        # escapes the lockset algorithm entirely
        self._rc_check(f"[{k!r}]", write=True)
        del self._rc_obj[k]

    def pop(self, *args, **kwargs):
        # ditto for pop: via __getattr__ it records a READ of "pop",
        # not the mutation of the popped key
        field = f"[{args[0]!r}]" if args else "pop"
        self._rc_check(field, write=True)
        return self._rc_obj.pop(*args, **kwargs)

    def __len__(self):
        self._rc_check("__len__", write=False)
        return len(self._rc_obj)

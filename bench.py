"""Flagship benchmark: ResNet-50 training throughput + MFU on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The reference published no machine-readable numbers (BASELINE.md:
"published: {}"), so ``vs_baseline`` is measured MFU against the north-star
target of 0.60 MFU from BASELINE.json (vs_baseline = MFU / 0.60).

FLOPs are taken from XLA's own cost analysis of the compiled step (not a
hand formula), so MFU accounting is honest for whatever model/config runs.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "cpu": 1e12,  # nominal, for CI runs off-TPU
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for key, val in PEAK_FLOPS.items():
        if kind.lower().startswith(key.lower()):
            return val
    return 100e12


def main():
    from distkeras_tpu.models import ResNet50
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    device = jax.devices()[0]
    on_tpu = device.platform != "cpu"
    batch = 128 if on_tpu else 4
    image = 224 if on_tpu else 64
    num_classes = 1000 if on_tpu else 10

    model = ResNet50(num_classes=num_classes)  # bf16 compute
    tx = resolve_optimizer("momentum", 0.1)
    x = jnp.ones((batch, image, image, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x[:2])
    state = TrainState.create(variables, tx, jax.random.key(1))

    step = make_train_step(model, "categorical_crossentropy", tx)
    labels = jnp.zeros((batch,), jnp.int32)
    batch_dict = {"features": x, "label": labels}

    jit_step = jax.jit(step, donate_argnums=0)
    lowered = jit_step.lower(state, batch_dict)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    flops_per_step = float(cost.get("flops", 0.0)) if cost else 0.0

    # Warmup, then timed steps.  NOTE: sync via a scalar fetch of the
    # final step's loss — on the tunneled TPU platform block_until_ready
    # can return before execution finishes, but a host transfer cannot
    # (the loss depends on the whole step chain).
    state, metrics = jit_step(state, batch_dict)
    state, metrics = jit_step(state, batch_dict)
    float(metrics["loss"])
    n_steps = 30 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = jit_step(state, batch_dict)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / n_steps

    images_per_sec = batch / dt
    mfu = (flops_per_step / dt) / peak_flops(device) \
        if flops_per_step else 0.0
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.60, 4),
        "mfu": round(mfu, 4),
        "step_time_ms": round(dt * 1e3, 2),
        "batch": batch,
        "image": image,
        "flops_per_step": flops_per_step,
        "device": getattr(device, "device_kind", str(device)),
        "loss_finite": bool(np.isfinite(float(metrics["loss"]))),
    }))


if __name__ == "__main__":
    main()

"""Flagship benchmark: ResNet-50 training throughput + MFU on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
``vs_baseline``/``mfu`` are null when the device kind has no known peak
(fabricating a peak would fabricate the metric — ADVICE.md r1).

The reference published no machine-readable numbers (BASELINE.md:
"published: {}"), so ``vs_baseline`` is measured MFU against the north-star
target of 0.60 MFU from BASELINE.json (vs_baseline = MFU / 0.60).

MFU accounting (see PERF.md): ``mfu`` uses the *analytic model FLOPs* —
2 x MACs x 3 for a training step (ResNet-50 fwd = 4.09 GMACs = 8.18
GFLOPs/image at 224px) — NOT XLA's executed-FLOPs counter.  The two agree
within ~3% at batch <= 512 (so the number is also *measured*-honest), but
XLA's counter inflates when the compiler adds rematerialization (at batch
1024 it reports ~30% more FLOPs while images/sec drops), which would let a
slower configuration "win".  Model FLOPs per image is the denominator that
tracks useful work.  Both numbers are reported.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.profiling import (
    peak_flops,
    resnet50_model_flops,
    time_step_chain,
)


def main():
    from distkeras_tpu.models import ResNet50
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    trace_path = os.environ.get("DKT_TELEMETRY_TRACE")
    if trace_path:
        telemetry.enable()

    device = jax.devices()[0]
    on_tpu = device.platform != "cpu"
    batch = 256 if on_tpu else 4
    image = 224 if on_tpu else 64
    num_classes = 1000 if on_tpu else 10

    # bf16 compute; space-to-depth stem re-layouts the 7x7/s2 stem conv
    # (same math/receptive field, different channel-summation order —
    # tests/test_models.py checks output parity to float tolerance via
    # s2d_stem_kernel) feeding the MXU 12 input channels instead of
    # 3 — measured ~1.5% faster end-to-end (PERF.md §9).
    model = ResNet50(num_classes=num_classes, stem="space_to_depth")
    tx = resolve_optimizer("momentum", 0.1)
    x = jnp.ones((batch, image, image, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x[:2])
    state = TrainState.create(variables, tx, jax.random.key(1))

    step = make_train_step(model, "categorical_crossentropy", tx)
    labels = jnp.zeros((batch,), jnp.int32)
    batch_dict = {"features": x, "label": labels}

    jit_step = jax.jit(step, donate_argnums=0)
    # telemetry consumer wiring: spans are no-ops unless the caller
    # enabled telemetry (DKT_TELEMETRY_TRACE dumps the timeline)
    with telemetry.span("bench_compile", batch=batch):
        compiled = jit_step.lower(state, batch_dict).compile()
    cost = compiled.cost_analysis()
    xla_flops_per_step = float(cost.get("flops", 0.0)) if cost else 0.0

    with telemetry.span("bench_timed_chain", n=30 if on_tpu else 3):
        dt, synced = time_step_chain(jit_step, state, batch_dict,
                                     n=30 if on_tpu else 3)

    images_per_sec = batch / dt
    model_flops_per_step = resnet50_model_flops(batch, image)
    peak, peak_known = peak_flops(device)
    mfu = model_flops_per_step / dt / peak if peak_known else None
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.60, 4) if peak_known else None,
        "mfu": round(mfu, 4) if peak_known else None,
        "xla_mfu": (round(xla_flops_per_step / dt / peak, 4)
                    if peak_known else None),
        "step_time_ms": round(dt * 1e3, 2),
        "batch": batch,
        "image": image,
        "model_flops_per_step": model_flops_per_step,
        "xla_flops_per_step": xla_flops_per_step,
        "device": getattr(device, "device_kind", str(device)),
        "peak_flops_known": peak_known,
        "metrics_finite": bool(np.isfinite(synced)),
    }))
    if trace_path:
        telemetry.tracer().write_chrome_trace(trace_path)


if __name__ == "__main__":
    main()

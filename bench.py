"""Flagship benchmark: ResNet-50 training throughput + MFU.

Prints ONE JSON line in the BENCH trajectory ``parsed`` format:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
``vs_baseline``/``mfu`` are null when the device kind has no known peak
(fabricating a peak would fabricate the metric — ADVICE.md r1).

Two arms (``--mode``):

* ``sync`` — the historical single-chip synchronous train step
  (metric ``resnet50_train_images_per_sec_per_chip``).
* ``ps-mesh`` — the compiled SPMD PS round (``fidelity="mesh"``,
  ISSUE 16): one worker per visible device, async ``MeshRoundDriver``
  dispatch, optional on-chip comm compression
  (``--comm-dtype``/``--comm-codec``).  Metric
  ``ps_round_images_per_sec_per_chip`` — the same unit the flagship
  script reports, so its records and BENCH records compare under
  ``perf_regress`` (which keys candidates by metric name).
* ``auto`` (default) — ``ps-mesh`` when more than one device is
  visible, else ``sync``; the bench path IS the mesh tier wherever a
  mesh exists.

The reference published no machine-readable numbers (BASELINE.md:
"published: {}"), so ``vs_baseline`` is measured MFU against the
north-star target of 0.60 MFU from BASELINE.json (vs_baseline =
MFU / 0.60) — identical semantics in both arms, with the mesh arm's
MFU accounted as analytic model FLOPs x n_chips (``profiling.train_mfu``).

MFU accounting (see PERF.md): ``mfu`` uses the *analytic model FLOPs* —
2 x MACs x 3 for a training step (ResNet-50 fwd = 4.09 GMACs = 8.18
GFLOPs/image at 224px) — NOT XLA's executed-FLOPs counter.  The two agree
within ~3% at batch <= 512 (so the number is also *measured*-honest), but
XLA's counter inflates when the compiler adds rematerialization (at batch
1024 it reports ~30% more FLOPs while images/sec drops), which would let a
slower configuration "win".  Model FLOPs per image is the denominator that
tracks useful work.  Both numbers are reported.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import attrib as attrib_lib
from distkeras_tpu import telemetry
from distkeras_tpu.profiling import (
    bench_device_config,
    peak_bandwidth,
    peak_flops,
    resnet50_model_flops,
    time_step_chain,
    train_mfu,
)


def _model_and_step(cfg):
    from distkeras_tpu.models import ResNet50
    from distkeras_tpu.workers import make_train_step, resolve_optimizer

    # bf16 compute; space-to-depth stem re-layouts the 7x7/s2 stem conv
    # (same math/receptive field, different channel-summation order —
    # tests/test_models.py checks output parity to float tolerance via
    # s2d_stem_kernel) feeding the MXU 12 input channels instead of
    # 3 — measured ~1.5% faster end-to-end (PERF.md §9).
    model = ResNet50(num_classes=cfg["num_classes"],
                     stem="space_to_depth")
    tx = resolve_optimizer("momentum", 0.1)
    step = make_train_step(model, "categorical_crossentropy", tx)
    return model, tx, step


def run_sync(cfg) -> dict:
    from distkeras_tpu.workers import TrainState

    device, on_tpu = cfg["device"], cfg["on_tpu"]
    batch, image = cfg["batch"], cfg["image"]
    model, tx, step = _model_and_step(cfg)
    x = jnp.ones((batch, image, image, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x[:2])
    state = TrainState.create(variables, tx, jax.random.key(1))
    labels = jnp.zeros((batch,), jnp.int32)
    batch_dict = {"features": x, "label": labels}

    jit_step = jax.jit(step, donate_argnums=0)
    # telemetry consumer wiring: spans are no-ops unless the caller
    # enabled telemetry (DKT_TELEMETRY_TRACE dumps the timeline)
    with telemetry.span("bench_compile", batch=batch):
        t_compile = time.perf_counter()
        compiled = jit_step.lower(state, batch_dict).compile()
        compile_s = time.perf_counter() - t_compile
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax: one dict per device
        cost = cost[0] if cost else {}
    xla_flops_per_step = float(cost.get("flops", 0.0)) if cost else 0.0

    with telemetry.span("bench_timed_chain", n=30 if on_tpu else 3):
        dt, synced = time_step_chain(jit_step, state, batch_dict,
                                     n=30 if on_tpu else 3)

    images_per_sec = batch / dt
    model_flops_per_step = resnet50_model_flops(batch, image)
    peak, peak_known = peak_flops(device)
    bw, bw_known = peak_bandwidth(device)
    mfu = train_mfu(images_per_sec, image, device)
    # roofline floor for THIS compiled step: XLA's flops against peak
    # compute, its bytes-accessed against peak memory bandwidth
    bytes_accessed = (float(cost.get("bytes accessed", 0.0))
                      if cost else 0.0)
    roof = attrib_lib.roofline(xla_flops_per_step, bytes_accessed,
                               peak, bw)
    mfu_roofline = attrib_lib.mfu(xla_flops_per_step,
                                  roof["t_roofline_s"], peak)
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.60, 4) if mfu is not None else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_roofline": (round(mfu_roofline, 4)
                         if mfu_roofline is not None else None),
        "xla_mfu": (round(xla_flops_per_step / dt / peak, 4)
                    if peak == peak else None),
        "step_time_ms": round(dt * 1e3, 2),
        "compile_s": round(compile_s, 3),
        "batch": batch,
        "image": image,
        "n_chips": 1,
        "mode": "sync",
        "model_flops_per_step": model_flops_per_step,
        "xla_flops_per_step": xla_flops_per_step,
        "device": getattr(device, "device_kind", str(device)),
        "peak_known": bool(peak_known and bw_known),
        "metrics_finite": bool(np.isfinite(synced)),
    }


def run_ps_mesh(cfg, comm_dtype: str, comm_codec,
                window: int = 2) -> dict:
    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.parallel import ps_dataplane
    from distkeras_tpu.parallel.ps_emulator import commit_permutation
    from distkeras_tpu.parallel.update_rules import RULES
    from distkeras_tpu.workers import TrainState

    device, on_tpu = cfg["device"], cfg["on_tpu"]
    batch, image = cfg["batch"], cfg["image"]
    W = cfg["n_devices"]
    model, tx, step = _model_and_step(cfg)
    x = jnp.ones((2, image, image, 3), jnp.float32)
    center = model.init(jax.random.key(0), x)["params"]
    rule = RULES["downpour"]()

    placement = mesh_lib.place_workers(W)
    dp = ps_dataplane.MeshDataplane(
        rule, step, placement.mesh, center, comm_dtype=comm_dtype,
        comm_codec=comm_codec)

    def make_worker(rng):
        return TrainState.create({"params": center}, tx, rng)

    mps, mws = dp.to_device(
        rule.init_state(center),
        jax.vmap(make_worker)(jax.random.split(jax.random.key(1), W)))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    batch_dict = jax.device_put(
        {"features": jnp.ones((W, window, batch, image, image, 3),
                              jnp.float32),
         "label": jnp.zeros((W, window, batch), jnp.int32)}, row)
    perm = jax.device_put(
        commit_permutation(jax.random.key(2), W), rep)

    driver = ps_dataplane.MeshRoundDriver(dp, mps, mws)
    reps = 10 if on_tpu else 3
    with telemetry.span("bench_mesh_warmup", workers=W):
        driver.dispatch(batch_dict, perm)
        driver.drain()
    with telemetry.span("bench_mesh_timed_rounds", n=reps):
        t0 = time.perf_counter()
        for _ in range(reps):
            driver.dispatch(batch_dict, perm)
        metrics = driver.drain()  # blocks on the last round's ring
        dt = (time.perf_counter() - t0) / reps

    # attribution pass OUTSIDE the timed window: flip sampling on for
    # one extra round to decompose it (host_gap/dispatch/compute/fetch
    # + the mfu_observed-vs-roofline pair off the cost ledger)
    driver.attrib_every = 1
    with telemetry.span("bench_mesh_attrib", workers=W):
        driver.dispatch(batch_dict, perm)
        metrics += driver.drain()
    attrib = driver.last_attrib or {}
    report = dp.cost_report()
    cost0 = report[0] if report else {}

    images_per_round = W * window * batch
    images_per_sec_chip = images_per_round / dt / W
    mfu = train_mfu(images_per_sec_chip * W, image, device, n_chips=W)
    losses = np.concatenate([m["loss"] for m in metrics])
    return {
        "metric": "ps_round_images_per_sec_per_chip",
        "value": round(images_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.60, 4) if mfu is not None else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "round_ms": round(dt * 1e3, 2),
        "step_time_ms": round(dt / window * 1e3, 2),
        "batch": batch,
        "image": image,
        "workers": W,
        "window": window,
        "n_chips": W,
        "mode": "ps-mesh",
        "comm_dtype": comm_dtype,
        "comm_codec": comm_codec,
        "comm_bytes_per_round": dp.comm_bytes_per_round,
        "comm_bytes_saved_per_round": dp.comm_bytes_saved_per_round,
        "mfu_roofline": (round(attrib["mfu_roofline"], 4)
                         if "mfu_roofline" in attrib else None),
        "mfu_observed": (round(attrib["mfu_observed"], 4)
                         if "mfu_observed" in attrib else None),
        "attrib": {seg: round(attrib[seg] * 1e3, 3)
                   for seg in ("host_gap", "dispatch",
                               "device_compute", "ring_fetch")
                   if seg in attrib},
        "compile_s": (round(cost0["compile_s"], 3)
                      if "compile_s" in cost0 else None),
        "device": getattr(device, "device_kind", str(device)),
        "peak_known": bool(cost0.get("peak_known", False)),
        "metrics_finite": bool(np.isfinite(losses).all()),
    }


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mode", default="auto",
                        choices=("auto", "sync", "ps-mesh"),
                        help="auto: ps-mesh when >1 device is visible")
    parser.add_argument("--comm-dtype", default="float32",
                        help="mesh arm delta wire dtype "
                             "(float32|bfloat16)")
    parser.add_argument("--comm-codec", default=None,
                        help="mesh arm center broadcast codec (int8)")
    args = parser.parse_args()

    trace_path = os.environ.get("DKT_TELEMETRY_TRACE")
    if trace_path:
        telemetry.enable()

    cfg = bench_device_config()
    mode = args.mode
    if mode == "auto":
        mode = "ps-mesh" if cfg["n_devices"] > 1 else "sync"
    if mode == "ps-mesh":
        record = run_ps_mesh(cfg, args.comm_dtype, args.comm_codec)
    else:
        record = run_sync(cfg)
    print(json.dumps(record))
    if trace_path:
        telemetry.tracer().write_chrome_trace(trace_path)


if __name__ == "__main__":
    main()
